"""Distributed tracing + fleet observability plane (ISSUE 14,
docs/Observability.md "Distributed tracing" / "Fleet metrics & SLO").

Stub replicas (tests/fleet_stub.py, no jax) exercise the cross-process
half — context propagation through router retries, span envelopes,
aggregator scrapes — in milliseconds; SloTracker/SpanAssembler/
parse_prometheus_text are unit-tested with injected clocks and pages;
the error-correlation contract (trace_id on every error reply) runs
against a real frontend with no models loaded (no compiles needed).
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.observability import set_event_logger
from lightgbm_tpu.observability.events import EventLogger
from lightgbm_tpu.observability.registry import (MetricsRegistry,
                                                 global_registry)
from lightgbm_tpu.observability.tracing import (SloTracker, SpanAssembler,
                                                TraceContext, make_span)
from lightgbm_tpu.observability.prom import (parse_prometheus_text,
                                             render_prometheus)
from lightgbm_tpu.serving import (FleetAggregator, ReplicaFleet, Router,
                                  ServingDaemon, serve_counters_reset,
                                  start_frontend)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STUB = os.path.join(REPO, "tests", "fleet_stub.py")


def _mk_fleet(workdir, n=2, envs=None, entries=(("m", "scale1"),)):
    fault_envs = {}
    for i in range(n):
        e = {"STUB_READY_FILE": os.path.join(
            str(workdir), f"replica-{i}.ready.json")}
        e.update((envs or {}).get(i, {}))
        fault_envs[i] = e
    return ReplicaFleet(
        n, list(entries), str(workdir), max_restarts=2,
        health_interval_s=0.1,
        spawn_cmd=lambda idx, rf: [sys.executable, STUB],
        fault_envs=fault_envs)


def _mk_router(fleet, **overrides):
    p = {"serve_retry_max": 3, "serve_retry_backoff_ms": 5.0,
         "serve_request_timeout_s": 15.0, "serve_trace_sample": 1}
    p.update(overrides)
    return Router(fleet, Config(p))


ROWS = np.arange(12, dtype=np.float64).reshape(3, 4)


@pytest.fixture(autouse=True)
def _reset_counters():
    serve_counters_reset()
    for key in ("router_requests", "router_retries", "router_failed",
                "slo_burn_total"):
        global_registry.inc(key, -global_registry.counter(key))
    yield
    set_event_logger(None)


# ------------------------------------------------------------ unit: context
def test_trace_context_wire_round_trip_and_child():
    ctx = TraceContext.new(sampled=True)
    back = TraceContext.from_wire(ctx.to_wire())
    assert (back.trace_id, back.span_id, back.sampled) == \
        (ctx.trace_id, ctx.span_id, True)
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.parent_id == ctx.span_id
    assert child.span_id != ctx.span_id
    # malformed wire fields parse to None, never raise
    assert TraceContext.from_wire(None) is None
    assert TraceContext.from_wire({"id": "x"}) is None
    assert TraceContext.from_wire("garbage") is None
    # ids are unique across contexts
    ids = {TraceContext.new().trace_id for _ in range(64)}
    assert len(ids) == 64


def test_make_span_drops_none_attrs_and_clamps_duration():
    ctx = TraceContext.new(sampled=True)
    s = make_span(ctx, "x", 10.0, 9.0, replica=3, backoff_ms=None)
    assert s["dur_ms"] == 0.0          # negative wall delta clamps
    assert s["attrs"] == {"replica": 3}
    assert s["trace_id"] == ctx.trace_id and s["pid"] == os.getpid()


# -------------------------------------------------------- unit: assembler
def test_assembler_waterfall_monotone_and_bounded():
    asm = SpanAssembler(capacity=8)
    ctx = TraceContext.new(sampled=True)
    # deliberately out of order: the assembler must sort and offset
    spans = [make_span(ctx.child(), "late", 105.0, 106.0),
             make_span(ctx.child(), "early", 100.0, 101.0),
             make_span(ctx.child(), "mid", 102.5, 103.0)]
    tr = asm.assemble(ctx.trace_id, spans, outcome="ok")
    rels = [s["rel_ms"] for s in tr["spans"]]
    assert rels == sorted(rels) and rels[0] == 0.0
    assert [s["name"] for s in tr["spans"]] == ["early", "mid", "late"]
    assert asm.get(ctx.trace_id)["outcome"] == "ok"
    assert asm.latest()["trace_id"] == ctx.trace_id
    # bounded retention: old ids evict, newest survive
    for _ in range(20):
        c = TraceContext.new(sampled=True)
        asm.assemble(c.trace_id, [make_span(c.child(), "s", 0.0, 1.0)])
    assert len(asm.ids()) == 8
    assert asm.get(ctx.trace_id) is None


# ------------------------------------------------------------- unit: SLO
def test_slo_tracker_multi_window_burn_and_event(tmp_path):
    set_event_logger(EventLogger(str(tmp_path), rank=0))
    global_registry.inc("slo_burn_total",
                        -global_registry.counter("slo_burn_total"))
    t = SloTracker(p99_ms=100.0, error_pct=1.0, fast_window_s=10.0,
                   slow_window_s=100.0, burn_threshold=1.0)
    # healthy traffic: fast latencies, no burn
    for i in range(64):
        t.observe(10.0, ok=True, now=float(i) * 0.1)
    assert not t.evaluate(now=6.4)
    assert global_registry.gauge("fleet_slo_burning") == 0.0
    # an acute breach: slow + failed requests swamp the 1% budget in
    # BOTH windows -> burning, exactly one slo_burn on the transition
    for i in range(32):
        t.observe(500.0, ok=(i % 2 == 0), now=7.0 + i * 0.01)
    assert t.evaluate(now=7.5)
    assert t.burning
    assert global_registry.gauge("fleet_slo_burning") == 1.0
    assert global_registry.counter("slo_burn_total") == 1
    rates = t.burn_rates(now=7.5)
    assert rates["fast"] > 1.0 and rates["slow"] > 1.0
    # still burning: no second event
    t.observe(500.0, ok=False, now=7.6)
    t.evaluate(now=7.6)
    assert global_registry.counter("slo_burn_total") == 1
    # windows drain past the breach -> cleared
    assert not t.evaluate(now=500.0)
    assert global_registry.gauge("fleet_slo_burning") == 0.0
    set_event_logger(None)
    events = [json.loads(ln) for ln in
              open(tmp_path / "events-rank0.jsonl")]
    burns = [e for e in events if e["event"] == "slo_burn"]
    assert len(burns) == 1
    assert burns[0]["slo_p99_ms"] == 100.0
    assert burns[0]["burn_rate_fast"] > 1.0


def test_slo_tracker_disabled_is_inert():
    t = SloTracker(p99_ms=0.0)
    t.observe(1e9, ok=False)
    assert not t.evaluate()
    assert not t.enabled


# ------------------------------------------- unit: prom parse + aggregator
def test_parse_prometheus_round_trips_render():
    reg = MetricsRegistry()
    reg.inc("serve_requests", 41)
    reg.inc("serve_requests_by_model::higgs", 17)
    reg.set_gauge("queue_depth", 3)
    page = render_prometheus(registry=reg)
    parsed = parse_prometheus_text(page)
    assert parsed["counters"]["lgbm_serve_requests"] == 41
    assert parsed["counters"][
        'lgbm_serve_requests_by_model{model="higgs"}'] == 17
    assert parsed["gauges"]["lgbm_queue_depth"] == 3
    # junk lines are skipped, not fatal
    assert parse_prometheus_text("!! not a metric\nx y z\n") == \
        {"counters": {}, "gauges": {}}


def test_fleet_aggregator_merges_counters_exactly():
    agg = FleetAggregator()
    r0 = MetricsRegistry()
    r0.inc("serve_requests", 30)
    r0.inc("serve_rows", 120)
    r0.inc("serve_requests_by_model::m", 30)
    r1 = MetricsRegistry()
    r1.inc("serve_requests", 12)
    r1.inc("serve_requests_by_model::m", 12)
    agg.record_scrape(0, render_prometheus(registry=r0))
    agg.record_scrape(1, render_prometheus(registry=r1))
    merged = agg.merged_counters()
    assert merged["lgbm_serve_requests"] == 42
    assert merged["lgbm_serve_rows"] == 120        # only replica 0 had it
    assert merged['lgbm_serve_requests_by_model{model="m"}'] == 42
    assert agg.replica_counter(1, "lgbm_serve_requests") == 12
    # a forgotten (down/relaunched) replica stops counting
    agg.forget(0)
    assert agg.merged_counters()["lgbm_serve_requests"] == 12
    # rendered block: merged families + per-replica supervisor gauges
    desc = [{"idx": 0, "healthy": True, "ready": True, "down": False,
             "restarts": 0},
            {"idx": 1, "healthy": False, "ready": False, "down": True,
             "restarts": 2}]
    block = agg.render(desc)
    assert "lgbm_fleet_serve_requests 12" in block
    assert 'lgbm_fleet_replica_up{replica="0"} 0' in block \
        or 'lgbm_fleet_replica_up{replica="0"} 1' in block
    assert 'lgbm_fleet_replica_restarts{replica="1"} 2' in block
    for ln in block.splitlines():
        if ln and not ln.startswith("#"):
            assert len(ln.rsplit(" ", 1)) == 2     # well-formed lines


# --------------------------------------- stub fleet: propagation + retry
def test_trace_survives_retry_onto_second_replica(tmp_path):
    """The context stamped at the edge rides the retry: the assembled
    trace shows TWO attempt child spans (first shed, second ok) under
    one route span, plus the serving replica's serve span."""
    fleet = _mk_fleet(tmp_path, n=2,
                      envs={0: {"STUB_SHED": "1"}}).start()
    router = _mk_router(fleet)
    try:
        assert fleet.wait_ready(timeout=20)
        retried = None
        for _ in range(8):
            r = router.predict("m", ROWS)
            assert r.trace_id
            tr = router.assembler.get(r.trace_id)
            assert tr is not None
            if r.retries >= 1:
                retried = tr
                break
        assert retried is not None, "no request hit the shedding replica"
        names = [s["name"] for s in retried["spans"]]
        attempts = [s for s in retried["spans"] if s["name"] == "attempt"]
        assert len(attempts) == 2
        outcomes = [a["attrs"]["outcome"] for a in attempts]
        assert outcomes.count("shed") == 1 and outcomes.count("ok") == 1
        # the two attempts hit DIFFERENT replicas
        assert len({a["attrs"]["replica"] for a in attempts}) == 2
        assert names.count("route") == 1
        serves = [s for s in retried["spans"] if s["name"] == "serve"]
        assert len(serves) == 1                      # one served span
        # the replica's span came from ANOTHER process and parents under
        # the attempt that succeeded
        ok_attempt = next(a for a in attempts
                          if a["attrs"]["outcome"] == "ok")
        assert serves[0]["pid"] != os.getpid()
        assert serves[0]["parent_id"] == ok_attempt["span_id"]
        assert len(retried["processes"]) == 2
        # waterfall is monotone
        rels = [s["rel_ms"] for s in retried["spans"]]
        assert rels == sorted(rels) and all(r >= 0 for r in rels)
    finally:
        router.stop()
        fleet.stop(drain=False)


def test_sampling_honors_serve_trace_sample(tmp_path):
    fleet = _mk_fleet(tmp_path, n=1).start()
    router = _mk_router(fleet, serve_trace_sample=4)
    try:
        assert fleet.wait_ready(timeout=20)
        for _ in range(8):
            r = router.predict("m", ROWS)
            assert r.trace_id      # ids stamp EVERY request...
        assert len(router.assembler.ids()) == 2   # ...spans every 4th
        # sample=0 turns span assembly off entirely
        router2 = _mk_router(fleet, serve_trace_sample=0)
        for _ in range(4):
            router2.predict("m", ROWS)
        assert router2.assembler.ids() == []
    finally:
        router.stop()
        fleet.stop(drain=False)


def test_router_error_carries_trace_id(tmp_path):
    fleet = _mk_fleet(tmp_path, n=1).start()
    router = _mk_router(fleet)
    try:
        assert fleet.wait_ready(timeout=20)
        with pytest.raises(RuntimeError) as ei:
            # strings break the stub's sum() -> non-retryable error
            router.predict("m", [["not", "numbers", "x", "y"]])
        assert getattr(ei.value, "trace_id", None)
        # the failure assembled a partial waterfall findable by id
        tr = router.assembler.get(ei.value.trace_id)
        assert tr is not None and tr["outcome"] == "error"
    finally:
        router.stop()
        fleet.stop(drain=False)


def test_aggregator_scrapes_stub_replicas_on_probe_tick(tmp_path):
    fleet = _mk_fleet(tmp_path, n=2).start()
    router = _mk_router(fleet)
    try:
        assert fleet.wait_ready(timeout=20)
        n_req = 6
        for _ in range(n_req):
            router.predict("m", ROWS)
        assert fleet.scrape_all() == 2
        snap = fleet.aggregator.snapshot()
        assert set(snap) == {0, 1}
        per = {i: s["counters"]["lgbm_serve_requests"]
               for i, s in snap.items()}
        merged = fleet.aggregator.merged_counters()["lgbm_serve_requests"]
        assert merged == sum(per.values()) == n_req
        assert all(v > 0 for v in per.values())   # round robin hit both
        # probe loop keeps the aggregator warm without scrape_all too
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if len(fleet.aggregator.snapshot()) == 2:
                break
            time.sleep(0.05)
        assert len(fleet.aggregator.snapshot()) == 2
        # the router's op=metrics page carries the merged family
        from lightgbm_tpu.observability import render_prometheus as rp
        page = rp(gauges_cb=router._metric_gauges,
                  text_cb=router._fleet_metrics_block)
        assert f"lgbm_fleet_serve_requests {n_req}" in page
    finally:
        router.stop()
        fleet.stop(drain=False)


# ------------------------------------ real frontend: error trace_id echo
def test_frontend_error_reply_echoes_trace_id():
    """A replica-side failure (unknown model here — no model load, no
    compile) must answer with the request's trace_id so the client can
    grep replica logs / the flight recorder for it (ISSUE 14
    satellite)."""
    import socket
    d = ServingDaemon(Config({"verbosity": -1})).start()
    srv = start_frontend(d, port=0)
    try:
        ctx = TraceContext.new(sampled=True)
        with socket.create_connection(
                ("127.0.0.1", srv.server_address[1]), timeout=10) as s:
            f = s.makefile("rwb")
            f.write((json.dumps(
                {"model": "nope", "rows": [[1.0, 2.0]],
                 "trace": ctx.to_wire()}) + "\n").encode())
            f.flush()
            reply = json.loads(f.readline())
        assert reply["ok"] is False
        assert reply["trace_id"] == ctx.trace_id
    finally:
        srv.shutdown()
        d.stop(drain=False)


def test_trace_assembled_event_lands_in_event_log(tmp_path):
    set_event_logger(EventLogger(str(tmp_path), rank=0))
    asm = SpanAssembler()
    ctx = TraceContext.new(sampled=True)
    asm.assemble(ctx.trace_id,
                 [make_span(ctx.child(), "route", 1.0, 2.0)],
                 model="m", outcome="ok")
    set_event_logger(None)
    events = [json.loads(ln) for ln in
              open(tmp_path / "events-rank0.jsonl")]
    ta = [e for e in events if e["event"] == "trace_assembled"]
    assert len(ta) == 1 and ta[0]["trace_id"] == ctx.trace_id
