import numpy as np

from lightgbm_tpu.io.binning import MISSING_NAN, MISSING_NONE
from lightgbm_tpu.models.tree import Tree


def _build_simple_tree():
    # root split on f0 <= 0.5; left leaf -1.0; right split on f1 <= 2.0 -> (2.0, 3.0)
    t = Tree(max_leaves=4)
    t.split(leaf=0, inner_feature=0, real_feature=0, threshold_bin=1,
            threshold_double=0.5, left_value=-1.0, right_value=1.0,
            left_cnt=10, right_cnt=20, left_weight=10.0, right_weight=20.0,
            gain=5.0, missing_type=MISSING_NONE, default_left=False)
    t.split(leaf=1, inner_feature=1, real_feature=1, threshold_bin=3,
            threshold_double=2.0, left_value=2.0, right_value=3.0,
            left_cnt=12, right_cnt=8, left_weight=12.0, right_weight=8.0,
            gain=3.0, missing_type=MISSING_NONE, default_left=False)
    return t


def test_predict_simple():
    t = _build_simple_tree()
    X = np.array([[0.0, 0.0], [1.0, 1.0], [1.0, 3.0]])
    np.testing.assert_allclose(t.predict(X), [-1.0, 2.0, 3.0])


def test_leaf_index():
    t = _build_simple_tree()
    X = np.array([[0.0, 0.0], [1.0, 1.0], [1.0, 3.0]])
    assert list(t.get_leaf_index(X)) == [0, 1, 2]


def test_missing_default_direction():
    t = Tree(max_leaves=2)
    t.split(leaf=0, inner_feature=0, real_feature=0, threshold_bin=1,
            threshold_double=0.5, left_value=-1.0, right_value=1.0,
            left_cnt=1, right_cnt=1, left_weight=1.0, right_weight=1.0,
            gain=1.0, missing_type=MISSING_NAN, default_left=True)
    X = np.array([[np.nan], [0.0], [1.0]])
    np.testing.assert_allclose(t.predict(X), [-1.0, -1.0, 1.0])


def test_shrinkage():
    t = _build_simple_tree()
    t.apply_shrinkage(0.1)
    X = np.array([[0.0, 0.0]])
    np.testing.assert_allclose(t.predict(X), [-0.1])


def test_text_roundtrip():
    t = _build_simple_tree()
    text = t.to_string(0)
    assert text.startswith("Tree=0\n")
    t2 = Tree.from_string(text)
    X = np.random.RandomState(0).normal(size=(50, 2))
    np.testing.assert_allclose(t.predict(X), t2.predict(X))
    assert t2.num_leaves == 3


def test_categorical_split_predict():
    t = Tree(max_leaves=2)
    t.split_categorical(leaf=0, inner_feature=0, real_feature=0,
                        bins_in_left=[1, 3], cats_in_left=[2, 5],
                        left_value=1.0, right_value=-1.0, left_cnt=5, right_cnt=5,
                        left_weight=5.0, right_weight=5.0, gain=2.0,
                        missing_type=MISSING_NAN)
    X = np.array([[2.0], [5.0], [3.0], [np.nan], [-1.0]])
    np.testing.assert_allclose(t.predict(X), [1.0, 1.0, -1.0, -1.0, -1.0])


def test_json():
    t = _build_simple_tree()
    j = t.to_json(0)
    assert j["num_leaves"] == 3
    assert j["tree_structure"]["split_feature"] == 0


def test_model_loader_rejects_garbage_cleanly():
    """Malformed model text must raise LightGBMError (or ValueError from
    numeric parsing), never segfault or produce a silent half-model
    (ref: gbdt_model_text.cpp LoadModelFromString's Log::Fatal paths)."""
    import pytest
    import lightgbm_tpu as lgb
    cases = [
        "",                                     # empty
        "not a model at all",
        "tree\nversion=v4\n",                   # headers only, no trees
        "tree\nversion=v4\nnum_class=1\nTree=0\nnum_leaves=2\n",  # truncated tree
    ]
    for txt in cases:
        with pytest.raises((lgb.LightGBMError, ValueError, KeyError,
                            IndexError)):
            lgb.Booster(model_str=txt)


def test_model_roundtrip_after_garbage_attempt():
    """A failed load must not poison subsequent valid loads."""
    import lightgbm_tpu as lgb
    import numpy as np
    rng = np.random.RandomState(0)
    X = rng.rand(300, 3)
    y = X[:, 0]
    b = lgb.train({"objective": "regression", "num_leaves": 7,
                   "verbosity": -1, "min_data_in_leaf": 5},
                  lgb.Dataset(X, label=y), num_boost_round=2)
    txt = b.model_to_string()
    try:
        lgb.Booster(model_str="garbage")
    except Exception:
        pass
    b2 = lgb.Booster(model_str=txt)
    np.testing.assert_allclose(b2.predict(X), b.predict(X), rtol=1e-6)


def test_zero_tree_model_roundtrips():
    """Zero-iteration saves carry the end-of-trees marker and must load
    (the garbage fatal only rejects marker-less header junk)."""
    import lightgbm_tpu as lgb
    import numpy as np
    rng = np.random.RandomState(0)
    X = rng.rand(100, 3)
    b = lgb.train({"objective": "regression", "verbosity": -1},
                  lgb.Dataset(X, label=X[:, 0]), num_boost_round=0)
    b2 = lgb.Booster(model_str=b.model_to_string())
    assert b2.num_trees() == 0
