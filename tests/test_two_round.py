"""Out-of-core (two_round) training ingestion (ref: config.h two_round;
dataset_loader.cpp:1022 SampleTextDataFromFile, :1100
ExtractFeaturesFromFile; Experiments.rst:160 two_round peak-RAM table):
the training file is streamed twice and the raw float matrix never
materializes."""

import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _write_file(path, n, F, seed=0):
    rng = np.random.RandomState(seed)
    with open(path, "w") as f:
        step = 50_000
        for i in range(0, n, step):
            c = min(step, n - i)
            X = rng.randn(c, F).astype(np.float32)
            y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.int32)
            block = np.column_stack([y.astype(np.float32), X])
            f.write("\n".join(
                "\t".join(f"{v:.5g}" for v in row) for row in block))
            f.write("\n")


def test_two_round_matches_in_memory(tmp_path):
    """When the bin sample covers every row the two paths see identical
    data, so mappers, codes, labels, and the trained model must match."""
    path = str(tmp_path / "small.tsv")
    _write_file(path, 5000, 8)
    ds_mem = lgb.Dataset(path)
    ds_two = lgb.Dataset(path, params={"two_round": True})
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    b_mem = lgb.train(params, ds_mem, num_boost_round=5)
    b_two = lgb.train(params, ds_two, num_boost_round=5)
    dm, dt = ds_mem.construct()._core, ds_two.construct()._core
    np.testing.assert_array_equal(np.asarray(dm.binned, np.int32),
                                  np.asarray(dt.binned, np.int32))
    np.testing.assert_array_equal(dm.metadata.label, dt.metadata.label)
    assert (b_mem.model_to_string().split("\nparameters:")[0]
            == b_two.model_to_string().split("\nparameters:")[0])


_RSS_SCRIPT = r"""
import sys
sys.path.insert(0, {repo!r})
import numpy as np
import jax
jax.devices()  # initialize the backend BEFORE the baseline snapshot
import lightgbm_tpu as lgb

def _status(key):
    for line in open("/proc/self/status"):
        if line.startswith(key + ":"):
            return int(line.split()[1]) * 1024
    return 0

two_round = sys.argv[1] == "two"
open("/proc/self/clear_refs", "w").write("5")   # reset VmHWM
base = _status("VmRSS")
ds = lgb.Dataset({path!r}, params={{"two_round": two_round,
                                    "bin_construct_sample_cnt": 20000}})
d = ds.construct()._core
assert d.num_data == {n}, d.num_data
print(_status("VmHWM") - base)
"""


def test_two_round_bounded_memory(tmp_path):
    """Pin the out-of-core property: loading a file whose raw float64
    matrix is ~120 MB must cost far less resident memory under two_round
    than the in-memory path (which holds the text lines + the float
    matrix), and absolutely less than half the raw matrix."""
    n, F = 300_000, 50
    path = str(tmp_path / "big.tsv")
    _write_file(path, n, F)
    raw_bytes = n * F * 8
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def run(mode):
        script = _RSS_SCRIPT.format(repo=repo, path=path, n=n)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)  # no virtual-device client inflation
        out = subprocess.run([sys.executable, "-c", script, mode],
                             capture_output=True, text=True, env=env,
                             check=True)
        return int(out.stdout.strip().splitlines()[-1])

    delta_two = run("two")
    delta_mem = run("mem")
    # two_round keeps only chunk + sample + uint8 codes resident
    # (measured ~65 MB vs ~286 MB for the in-memory path at these shapes)
    assert delta_two < raw_bytes * 0.75, (delta_two, raw_bytes)
    # and clearly beats the in-memory path (lines + float64 matrix)
    assert delta_two < delta_mem - raw_bytes * 0.5, (delta_two, delta_mem)


def test_two_round_libsvm_late_wide_feature(tmp_path):
    """Sparse LibSVM reveals its max feature index late; the streaming
    loader must widen with implicit zeros instead of dying."""
    path = str(tmp_path / "wide.svm")
    rng = np.random.RandomState(0)
    with open(path, "w") as f:
        for i in range(2000):
            y = rng.randint(0, 2)
            f.write(f"{y} 0:{rng.rand():.4f} 2:{rng.rand():.4f}\n")
        # feature 9 first appears on the very last row
        f.write("1 0:0.5 9:1.25\n")
    ds = lgb.Dataset(path, params={"two_round": True})
    core = ds.construct()._core
    assert core.num_data == 2001
    assert core.num_total_features == 10
    b = lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": -1}, ds, num_boost_round=3)
    assert np.isfinite(b.predict(np.zeros((2, 10)))).all()


def test_two_round_header_names_and_categoricals(tmp_path):
    """Header names survive two_round and name: categorical tokens
    resolve against them (parity with the in-memory loader)."""
    path = str(tmp_path / "hdr.csv")
    rng = np.random.RandomState(1)
    with open(path, "w") as f:
        f.write("target,alpha,cat1\n")
        for i in range(1500):
            f.write(f"{rng.randint(0, 2)},{rng.rand():.4f},"
                    f"{rng.randint(0, 5)}\n")
    p = {"two_round": True, "header": True, "label_column": "name:target",
         "categorical_feature": "name:cat1", "min_data_in_leaf": 5}
    ds = lgb.Dataset(path, params=p)
    core = ds.construct()._core
    assert core.feature_names == ["alpha", "cat1"]
    from lightgbm_tpu.io.binning import BIN_CATEGORICAL
    assert core.bin_mappers[1].bin_type == BIN_CATEGORICAL


def test_two_round_rejects_linear_tree(tmp_path):
    path = str(tmp_path / "small2.tsv")
    _write_file(path, 500, 4)
    with pytest.raises(Exception):
        lgb.Dataset(path, params={"two_round": True,
                                  "linear_tree": True}).construct()


def test_two_round_validation_set_streams(tmp_path):
    """two_round applies to validation files too (aligned to the
    training mappers, ref: LoadFromFileAlignWithOtherDataset): the eval
    results must match the in-memory valid load."""
    tr = str(tmp_path / "tr.tsv")
    va = str(tmp_path / "va.tsv")
    _write_file(tr, 4000, 6, seed=0)
    _write_file(va, 2000, 6, seed=9)
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "metric": "binary_logloss"}

    def run(two):
        ds = lgb.Dataset(tr, params={"two_round": two})
        vs = lgb.Dataset(va, params={"two_round": two}, reference=ds)
        rec = {}
        lgb.train(p, ds, num_boost_round=5, valid_sets=[vs],
                  callbacks=[lgb.record_evaluation(rec)])
        return rec["valid_0"]["binary_logloss"]

    np.testing.assert_allclose(run(True), run(False), rtol=1e-9)
