"""Decomposed (hi/lo outer-product) wave-histogram kernel
(ops/histogram.py _wave_kernel_hl): parity against a numpy scatter oracle
and against the full wave kernel.

The Pallas kernel needs real TPU hardware; under the CPU test platform
these tests skip (same gating as test_wave_int8.py — the driver bench
exercises the path on-device, and models were verified bit-identical with
the kernel on/off there)."""

import numpy as np
import pytest
import jax

pytestmark = pytest.mark.skipif(jax.default_backend() != "tpu",
                                reason="Pallas wave kernel needs TPU")


@pytest.mark.parametrize("S,out_slots", [(1, 8), (2, 8), (4, 8), (8, 8)])
def test_hl_wave_matches_scatter_oracle(S, out_slots):
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import build_histogram_wave_hl
    rng = np.random.RandomState(S)
    n, F, B = 1024 * 8, 12, 256
    binned = rng.randint(0, B, (F, n)).astype(np.uint8)
    # computed slots 0..S-1; everyone else carries a sentinel
    slot = rng.randint(0, 2 * S, n).astype(np.int32)
    slot = np.where(slot < S, slot, 10 ** 6).astype(np.int32)
    g = rng.randn(n).astype(np.float32)
    h = rng.rand(n).astype(np.float32)
    mask = (rng.rand(n) < 0.9).astype(np.float32)
    gh = np.stack([g * mask, h * mask, mask], 1).astype(np.float32)
    hist, cnt = build_histogram_wave_hl(
        jnp.asarray(binned), jnp.asarray(binned.T), jnp.asarray(slot),
        jnp.asarray(gh), max_bin=B, num_slots=S, out_slots=out_slots)
    assert hist.shape == (out_slots, F, B, 2)
    # oracle at the kernel's bf16 operand precision
    gb = np.asarray(jnp.asarray(gh[:, 0]).astype(jnp.bfloat16), np.float64)
    hb = np.asarray(jnp.asarray(gh[:, 1]).astype(jnp.bfloat16), np.float64)
    exp = np.zeros((out_slots, F, B, 2))
    inb = slot < S
    for f in range(F):
        np.add.at(exp[:, f, :, 0], (slot[inb], binned[f][inb]), gb[inb])
        np.add.at(exp[:, f, :, 1], (slot[inb], binned[f][inb]), hb[inb])
    np.testing.assert_allclose(np.asarray(hist, np.float64), exp,
                               rtol=1e-3, atol=1e-3)
    expc = np.bincount(slot[inb], weights=mask[inb], minlength=out_slots)
    np.testing.assert_array_equal(np.asarray(cnt), expc[:out_slots])


def test_hl_wave_matches_full_kernel():
    """hl and full kernels must agree (same bf16 operands, fp32 MXU
    accumulation) so the engine can switch per wave without model drift."""
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import (build_histogram_wave,
                                            build_histogram_wave_hl)
    rng = np.random.RandomState(0)
    n, F, B, S = 1024 * 8, 28, 256, 4
    binned = rng.randint(0, B, (F, n)).astype(np.uint8)
    slot = rng.randint(0, 2 * S, n).astype(np.int32)
    slot = np.where(slot < S, slot, 10 ** 6).astype(np.int32)
    gh = np.stack([rng.randn(n), rng.rand(n), np.ones(n)],
                  1).astype(np.float32)
    h1, c1 = build_histogram_wave_hl(
        jnp.asarray(binned), jnp.asarray(binned.T), jnp.asarray(slot),
        jnp.asarray(gh), max_bin=B, num_slots=S, out_slots=8)
    h2, c2 = build_histogram_wave(
        jnp.asarray(binned), jnp.asarray(slot), jnp.asarray(gh),
        max_bin=B, num_slots=8)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h2),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
