"""Quantized int8 wave-histogram kernel (ref: dense_bin.hpp:174
ConstructHistogramIntInner; gradient_discretizer.hpp): exact int32
accumulation through the MXU int8 path.

The Pallas kernel needs real TPU hardware; under the CPU test platform
these tests skip (the driver bench exercises the path on-device, and the
kernel was oracle-verified there: see PERF_NOTES.md)."""

import numpy as np
import pytest
import jax

pytestmark = pytest.mark.skipif(jax.default_backend() != "tpu",
                                reason="Pallas wave kernel needs TPU")


def test_int8_wave_matches_integer_oracle():
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import build_histogram_wave
    rng = np.random.RandomState(0)
    n, F, B, NL = 1024 * 16, 12, 64, 32
    qbins, qhalf = 4, 2
    gscale, hscale = 0.0123, 0.0456
    binned = rng.randint(0, B, (F, n)).astype(np.uint8)
    slot = rng.randint(0, NL, n).astype(np.int32)
    gi = rng.randint(-qhalf, qhalf + 1, n)
    hi = rng.randint(0, qbins + 1, n)
    mask = (rng.rand(n) < 0.9).astype(np.float32)
    gh = np.stack([gi * gscale * mask, hi * hscale * mask, mask],
                  1).astype(np.float32)
    h, c = build_histogram_wave(
        jnp.asarray(binned), jnp.asarray(slot), jnp.asarray(gh),
        max_bin=B, num_slots=NL, quant_bins=qbins,
        quant_scales=jnp.asarray([gscale, hscale], jnp.float32))
    exp = np.zeros((NL, F, B, 2))
    mi = mask.astype(np.int64)
    for f in range(F):
        np.add.at(exp[:, f, :, 0], (slot, binned[f]), gi * mi)
        np.add.at(exp[:, f, :, 1], (slot, binned[f]), hi * mi)
    exp[..., 0] *= gscale
    exp[..., 1] *= hscale
    np.testing.assert_allclose(np.asarray(h), exp, rtol=1e-6, atol=1e-7)
    np.testing.assert_array_equal(
        np.asarray(c), np.bincount(slot, mi, minlength=NL))


def test_quantized_wave_training_quality():
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(1)
    n, F = 100_000, 10
    X = rng.rand(n, F).astype(np.float32)
    y = (rng.rand(n) < 1 / (1 + np.exp(-4 * (X[:, 0] - 0.5)))).astype(
        np.float32)
    base = {"objective": "binary", "num_leaves": 63, "verbose": -1}
    b_fp = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=8)
    b_q = lgb.train({**base, "use_quantized_grad": True},
                    lgb.Dataset(X, label=y), num_boost_round=8)
    assert b_q._gbdt.grow_params.quant_bins > 0
    corr = np.corrcoef(b_fp.predict(X), b_q.predict(X))[0, 1]
    assert corr > 0.99, corr
