"""Wave-vs-leafwise engine parity: measured, bounded deviation.

The wave engine batches splits level-wise (learner/wave.py), so when the
num_leaves budget binds its trees allocate tail leaves more breadth-first
than the reference's strict leaf-wise gain order (serial_tree_learner.cpp:219
ArgMax leaf order).  The default wave_prune mode overgrows past the budget with the cheap
ladder and prunes back in the leaf-wise pop order simulated over the
overgrown gains — EXACTLY the leaf-wise tree whenever its splits lie in
the overgrown region.  Measured at bench scale (1M rows, 255 leaves, 13
iters on the v5e chip — PERF_NOTES.md, round 4):

  engine                        sec/iter   held-out AUC
  wave, wave_prune=false        0.1199     0.72730
  wave (prune, overshoot 1.5)   0.1382     0.72873
  wave (prune, overshoot 2.0)   0.1877     0.72956
  leafwise (parity engine)      0.958      0.73047
  reference CLI (same data)     0.2223 (1-core CPU) 0.73087

The leafwise engine matches the reference oracle's quality; the default
wave+prune engine trades a bounded AUC delta for ~7x speed.  This test
pins the bound at a CPU-tractable scale, asserts bit-exact leaf-wise
equivalence under full coverage, and asserts the tail-halving option
sits between plain wave and leafwise in budget allocation behavior.
"""

import numpy as np

import lightgbm_tpu as lgb

ROWS = 20_000
LEAVES = 127
ITERS = 8


def _data(seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(ROWS, 10).astype(np.float32)
    w = np.random.RandomState(7).randn(10)
    logit = X @ w + 0.8 * X[:, 0] * X[:, 1] + np.sin(2 * X[:, 2])
    y = (rng.rand(ROWS) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    return X, y


def _auc(y, s):
    order = np.argsort(s)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(s) + 1)
    pos = y > 0
    n_pos, n_neg = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def _train_auc(strategy, **extra):
    X, y = _data(0)
    Xte, yte = _data(1)
    params = {"objective": "binary", "num_leaves": LEAVES,
              "learning_rate": 0.1, "min_data_in_leaf": 20,
              "verbosity": -1, "tpu_growth_strategy": strategy, **extra}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=ITERS)
    return _auc(yte, b._gbdt.predict_raw(Xte)), b


def test_wave_auc_within_bound_of_leafwise():
    """Acceptance bound: the default (prune-mode) wave engine's held-out
    AUC is within 0.002 of the strict leaf-wise engine at 127 leaves
    (measured delta here is ~0.0003, at bench scale ~0.0017); the plain
    no-prune engine stays within the old 0.01 bound."""
    auc_wave, b_wave = _train_auc("wave")
    auc_leaf, b_leaf = _train_auc("leafwise")
    assert abs(auc_leaf - auc_wave) < 0.002, (auc_leaf, auc_wave)
    auc_plain, _ = _train_auc("wave", wave_prune=False)
    assert abs(auc_leaf - auc_plain) < 0.01, (auc_leaf, auc_plain)
    # quality mode (spike waves, PERF_NOTES round-5 frontier): within
    # 0.001 of leaf-wise
    auc_spike, _ = _train_auc("wave", wave_spike_reserve=16)
    assert auc_spike > auc_leaf - 0.001, (auc_leaf, auc_spike)
    # both engines spend the full leaf budget on this gain landscape
    mw = b_wave._gbdt.models_[0]
    ml = b_leaf._gbdt.models_[0]
    assert mw.num_leaves == LEAVES and ml.num_leaves == LEAVES


def test_tail_halving_tightens_the_gap():
    """wave_tail_halving spends at most half the remaining budget per
    wave once it binds: the first tree must take MORE waves' worth of
    splits (strictly later leaves get allocated by global gain), and
    quality must not regress vs plain wave beyond noise."""
    auc_wave, b_wave = _train_auc("wave")
    auc_half, b_half = _train_auc("wave", wave_tail_halving=True)
    # bounded: halving sits within noise of wave..leafwise
    assert auc_half > auc_wave - 0.005, (auc_half, auc_wave)
    # structural evidence the cap engaged: split_gain of the LAST splits
    # under halving dominates the plain wave's tail (later splits are
    # re-ranked globally instead of committed a wave early)
    gw = np.sort(np.asarray(b_wave._gbdt.models_[0].split_gain))
    gh = np.sort(np.asarray(b_half._gbdt.models_[0].split_gain))
    assert gh[:10].sum() >= gw[:10].sum() * 0.9


def test_leafwise_available_on_any_backend():
    """tpu_growth_strategy=leafwise is the documented reference-parity
    escape hatch; it must train on the CPU test backend too."""
    auc_leaf, b = _train_auc("leafwise")
    assert auc_leaf > 0.5
    assert b._gbdt.growth_strategy == "leafwise"


def test_wave_prune_exact_leafwise_under_full_coverage():
    """With a depth bound the overgrown ladder can explore every positive
    -gain split, and pruning must then reproduce the strict leaf-wise
    tree EXACTLY: same splits, same thresholds, same pop order, same
    node/leaf numbering, same row counts.  (Float leaf values agree to
    reduction-order noise only — the engines sum gradients in different
    orders.)"""
    X, y = _data(0)
    base = {"objective": "binary", "num_leaves": 15, "max_depth": 5,
            "verbosity": -1, "min_data_in_leaf": 20}
    b_lw = lgb.train({**base, "tpu_growth_strategy": "leafwise"},
                     lgb.Dataset(X, label=y), num_boost_round=4)
    b_wp = lgb.train({**base, "tpu_growth_strategy": "wave",
                      "wave_prune_overshoot": 2.2},
                     lgb.Dataset(X, label=y), num_boost_round=4)
    b_lw.model_to_string(); b_wp.model_to_string()  # pull device trees
    for m_lw, m_wp in zip(b_lw._gbdt.models_, b_wp._gbdt.models_):
        assert m_lw.num_leaves == m_wp.num_leaves
        for f in ("split_feature", "threshold_in_bin", "left_child",
                  "right_child", "leaf_count", "internal_count",
                  "decision_type"):
            np.testing.assert_array_equal(
                np.asarray(getattr(m_lw, f)), np.asarray(getattr(m_wp, f)),
                err_msg=f)
        np.testing.assert_allclose(np.asarray(m_lw.leaf_value),
                                   np.asarray(m_wp.leaf_value),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(m_lw.split_gain),
                                   np.asarray(m_wp.split_gain),
                                   rtol=1e-4, atol=1e-4)
