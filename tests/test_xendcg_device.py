"""Device rank_xendcg gradients (ranking.py RankXENDCG.make_device_grad_fn;
ref: rank_objective.hpp:362, cuda_rank_objective.cu:385-624).

The device program's math must equal the host _one_query formulas given
the SAME per-query uniform draws; the RNG streams themselves differ by
design (fold_in vs numpy RandomState, documented deviation)."""

import numpy as np
import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Metadata
from lightgbm_tpu.ranking import RankXENDCG


def _problem(seed=0, n_q=40):
    rng = np.random.RandomState(seed)
    lens = rng.randint(1, 40, n_q)
    n = int(lens.sum())
    labels = rng.randint(0, 5, n).astype(np.float64)
    score = rng.randn(n)
    return lens, n, labels, score


class _FixedRand:
    """RandomState stand-in feeding the device path's uniforms."""
    def __init__(self, u):
        self._u = u
    def random_sample(self, cnt):
        return np.asarray(self._u[:cnt], np.float64)


def test_device_xendcg_math_matches_host_given_same_uniforms():
    lens, n, labels, score = _problem()
    md = Metadata(n)
    md.set_label(labels)
    md.set_group(lens.astype(np.int64))
    obj = RankXENDCG(Config({"objective": "rank_xendcg",
                             "objective_seed": 11}))
    obj.init(md, n)
    n_pad = (n + 1023) // 1024 * 1024
    fn = obj.make_device_grad_fn(n_pad)
    sc = jnp.zeros((1, n_pad)).at[0, :n].set(jnp.asarray(score, jnp.float32))
    g, h = fn(sc, None)          # iteration 0 -> key fold_in(seed, 0)
    g = np.asarray(g)[0, :n]
    h = np.asarray(h)[0, :n]
    assert np.isfinite(g).all() and np.isfinite(h).all()

    # replicate the device draws per query and feed the HOST formulas
    key_it = jax.random.fold_in(jax.random.PRNGKey(11), 0)
    qb = obj.query_boundaries
    from lightgbm_tpu.metric import bucket_queries
    m_of = {}
    for b in bucket_queries(qb, n_pad):
        for q in b["qs"]:
            m_of[int(q)] = b["m"]
    g_ref = np.zeros(n)
    h_ref = np.zeros(n)
    for q in range(obj.num_queries):
        a, e = int(qb[q]), int(qb[q + 1])
        u = np.asarray(jax.random.uniform(
            jax.random.fold_in(key_it, q), (m_of[q],)), np.float64)
        obj.rands[q] = _FixedRand(u)
        # host math in float32 resolution to match the device program
        lq, hq = obj._one_query(q, labels[a:e],
                                score[a:e].astype(np.float32))
        g_ref[a:e], h_ref[a:e] = lq, hq
    np.testing.assert_allclose(g, g_ref, rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(h, h_ref, rtol=2e-3, atol=2e-4)


def test_device_xendcg_zero_for_single_doc_queries():
    lens = np.array([1, 5, 1, 7])
    n = int(lens.sum())
    rng = np.random.RandomState(1)
    labels = rng.randint(0, 4, n).astype(np.float64)
    md = Metadata(n)
    md.set_label(labels)
    md.set_group(lens.astype(np.int64))
    obj = RankXENDCG(Config({"objective": "rank_xendcg"}))
    obj.init(md, n)
    n_pad = 1024
    fn = obj.make_device_grad_fn(n_pad)
    sc = jnp.zeros((1, n_pad)).at[0, :n].set(
        jnp.asarray(rng.randn(n), jnp.float32))
    g, h = fn(sc, None)
    g = np.asarray(g)[0]
    assert g[0] == 0.0 and g[6] == 0.0          # single-doc queries
    assert np.abs(g[1:6]).sum() > 0             # real queries move
    assert np.abs(g[n:]).sum() == 0             # padding untouched


def test_device_xendcg_deterministic_per_iteration():
    lens, n, labels, score = _problem(seed=3)
    md = Metadata(n)
    md.set_label(labels)
    md.set_group(lens.astype(np.int64))
    obj = RankXENDCG(Config({"objective": "rank_xendcg"}))
    obj.init(md, n)
    n_pad = (n + 1023) // 1024 * 1024
    sc = jnp.zeros((1, n_pad)).at[0, :n].set(jnp.asarray(score, jnp.float32))
    fn1 = obj.make_device_grad_fn(n_pad)
    g1, _ = fn1(sc, None)
    obj2 = RankXENDCG(Config({"objective": "rank_xendcg"}))
    obj2.init(md, n)
    fn2 = obj2.make_device_grad_fn(n_pad)
    g2, _ = fn2(sc, None)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
    # successive iterations draw fresh uniforms
    g3, _ = fn1(sc, None)
    assert not np.array_equal(np.asarray(g1), np.asarray(g3))


def test_xendcg_training_quality_matches_host():
    b_dev = lgb.train(
        {"objective": "rank_xendcg", "num_leaves": 15, "verbosity": -1,
         "learning_rate": 0.1, "metric": "ndcg", "eval_at": [3]},
        lgb.Dataset("/root/reference/examples/lambdarank/rank.train"),
        num_boost_round=10)
    assert getattr(b_dev._gbdt, "_ranking_dev_fn", None), \
        "device path not engaged"
    orig = RankXENDCG.make_device_grad_fn
    RankXENDCG.make_device_grad_fn = lambda self, n: None
    try:
        b_host = lgb.train(
            {"objective": "rank_xendcg", "num_leaves": 15,
             "verbosity": -1, "learning_rate": 0.1, "metric": "ndcg",
             "eval_at": [3]},
            lgb.Dataset("/root/reference/examples/lambdarank/rank.train"),
            num_boost_round=10)
    finally:
        RankXENDCG.make_device_grad_fn = orig
    # quality proxy: training NDCG via booster eval on the SAME data
    d = dict(b_dev._gbdt.eval_train())["ndcg@3"]
    h = dict(b_host._gbdt.eval_train())["ndcg@3"]
    assert abs(d - h) < 0.03, (d, h)
