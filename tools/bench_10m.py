"""BASELINE workload bench: Higgs-scale 10M rows x 28 features x 255
leaves, >= 100 timed iterations on the real chip (BASELINE.md target #2;
ref docs/Experiments.rst:110-123 trains 10.5M rows in 0.260 s/iter on a
2015 28-core box).

Writes docs/bench_10m.json; bench.py folds the numbers into its single
driver JSON line.  Also derives the MFU/roofline accounting PERF_NOTES.md
reports: per-iteration streamed one-hot volume from the wave ladder
model, achieved bytes/s against the v5e's ~2 TB/s VMEM bandwidth, and
useful-MAC utilization.

Usage: python tools/bench_10m.py  [BENCH10M_ROWS=... BENCH10M_ITERS=...]
"""
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import FEATURES, _auc, make_higgs_like

ROWS = int(os.environ.get("BENCH10M_ROWS", 10_000_000))
ITERS = int(os.environ.get("BENCH10M_ITERS", 100))
WARMUP = 3
NUM_LEAVES = 255
MAX_BIN = 255
TEST_ROWS = 500_000


def ladder_volume_model(n, F=FEATURES, B=256, L=NUM_LEAVES, C=2,
                        overshoot=1.5):
    """LOWER-BOUND one-hot bytes streamed per iteration by the wave
    ladder: each kernel materializes its bin one-hot in VMEM once (1
    write) and the MXU reads it once (1 read) — 2 passes of the one-hot
    volume, which is provable from the kernel structure (the old model
    guessed 3.5-6x pass multipliers and produced bandwidth "fractions"
    above 1.0; see docs/bandwidth.json for the measured roof this bound
    is divided by).  Real traffic is strictly higher (slot-channel RHS,
    accumulator re-reads), so the reported fraction is a floor."""
    from lightgbm_tpu.ops.histogram import hl_split_of, wave_hl_profitable
    Lg = min(max(L, int(math.ceil(L * overshoot))), 4 * L)
    num_waves = max(1, math.ceil(math.log2(Lg)))
    kss = [min(1 << max(k - 1, 0), Lg) for k in range(num_waves)]
    kss.append(max(Lg // 2, 1))          # the while-loop tail wave
    units = 0.0
    for S in kss:
        if wave_hl_profitable(B, S, C):
            Bh, Bl = hl_split_of(B, S, C)
            units += 2.0 * F * (Bh + Bl * C * S)
        else:
            units += 2.0 * F * B
    return units * n * 2.0               # bf16 bytes


def main():
    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.observability.costmodel import (backend_peaks,
                                                      global_cost_model)

    X, y = make_higgs_like(ROWS, FEATURES)
    Xte, yte = make_higgs_like(TEST_ROWS, FEATURES, seed=1)
    params = {"objective": "binary", "num_leaves": NUM_LEAVES,
              "learning_rate": 0.1, "max_bin": MAX_BIN,
              "min_data_in_leaf": 20, "verbosity": -1, "metric": "none"}
    # compiled-cost harvesting ON for the whole run: the harvest is one
    # .lower().cost_analysis() per traced signature (warmup pays it),
    # then a dict add per call — the timed loop stays representative
    global_cost_model.enabled = True
    t0 = time.time()
    booster = lgb.Booster(params=params, train_set=lgb.Dataset(X, label=y))
    for _ in range(WARMUP):
        booster.update()
    _ = np.asarray(booster._gbdt.scores[0][:8])
    setup_s = time.time() - t0
    cost0 = global_cost_model.snapshot()
    t0 = time.time()
    for _ in range(ITERS):
        booster.update()
    _ = np.asarray(booster._gbdt.scores[0][:8])
    sec_per_iter = (time.time() - t0) / ITERS
    cost1 = global_cost_model.snapshot()
    auc = _auc(yte, booster._gbdt.predict_raw(Xte))

    bytes_per_iter = ladder_volume_model(ROWS)
    tbps = bytes_per_iter / sec_per_iter / 1e12
    # useful accumulation = one MAC per (row, feature, channel) per wave
    waves = max(1, math.ceil(math.log2(int(NUM_LEAVES * 1.5)))) + 1
    useful_macs = ROWS * FEATURES * 3 * waves
    mfu = useful_macs * 2 / sec_per_iter / 197e12  # v5e bf16 peak

    # MEASURED cross-check (observability/costmodel.py): XLA's own cost
    # analysis of the compiled programs that actually ran in the timed
    # loop, instead of the hand-counted MAC model above.  useful_mac_mfu
    # counts only the accumulation the algorithm NEEDS; measured_mfu
    # counts everything the compiled program DOES — the gap between
    # them is the one-hot overhead the Pallas-histogram item deletes.
    peak_flops, peak_bw = backend_peaks()
    meas_flops = meas_bytes = 0.0
    for group, tot in cost1.items():
        was = cost0.get(group, {"flops": 0.0, "bytes": 0.0})
        meas_flops += tot["flops"] - was["flops"]
        meas_bytes += tot["bytes"] - was["bytes"]
    meas_flops /= ITERS
    meas_bytes /= ITERS
    measured_mfu = meas_flops / sec_per_iter / peak_flops
    measured_ai = (meas_flops / meas_bytes) if meas_bytes > 0 else None
    ridge = peak_flops / peak_bw

    # measured roofs (tools/bench_bandwidth.py) replace the old nominal
    # 2 TB/s guess, whose "fraction" exceeded 1.0
    bw_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bandwidth.json")
    vmem_roof = hbm_roof = None
    if os.path.exists(bw_path):
        try:
            bw = json.load(open(bw_path))
            vmem_roof = bw.get("vmem_stream_tbps")
            hbm_roof = bw.get("hbm_stream_tbps")
        except (OSError, ValueError):
            pass

    # end-to-end wall clock: the reference's headline is the WHOLE run
    # (BASELINE.md: 130 s for 500 iterations on a 2015 28-core host,
    # setup included) — report setup + 500 iterations, extrapolated from
    # the measured steady state
    e2e_500 = setup_s + 500 * sec_per_iter

    out = {
        "rows": ROWS, "features": FEATURES, "num_leaves": NUM_LEAVES,
        "iters": WARMUP + ITERS, "sec_per_iter": round(sec_per_iter, 4),
        "rows_per_sec_per_iter": round(ROWS / sec_per_iter),
        "auc": round(auc, 5),
        "setup_s": round(setup_s, 1),
        "e2e_500iter_s": round(e2e_500, 1),
        "e2e_500iter_vs_baseline_28core_2015": round(
            (130.094 * ROWS / 10_500_000) / e2e_500, 4),
        "vs_baseline_28core_2015": round(
            (0.260194 * ROWS / 10_500_000) / sec_per_iter, 4),
        "min_streamed_bytes_per_iter": round(bytes_per_iter),
        "min_achieved_tbps": round(tbps, 3),
        "useful_mac_mfu": round(mfu, 5),
        # compiled-HLO cross-check: what XLA says the timed loop's
        # programs did, vs the analytic MAC count above
        "measured_mfu": round(measured_mfu, 7),
        "measured_flops_per_iter": round(meas_flops),
        "measured_bytes_per_iter": round(meas_bytes),
        "measured_arithmetic_intensity": (round(measured_ai, 4)
                                          if measured_ai is not None
                                          else None),
        "roofline_bound": ("unknown" if measured_ai is None
                           else "compute" if measured_ai >= ridge
                           else "hbm"),
        "measured_vs_useful_mac_ratio": (round(measured_mfu / mfu, 2)
                                         if mfu > 0 else None),
        "backend": jax.default_backend(),
        "measured_at": time.strftime("%Y-%m-%d"),
    }
    if vmem_roof:
        out["measured_vmem_roof_tbps"] = vmem_roof
        out["min_frac_of_measured_vmem_roof"] = round(tbps / vmem_roof, 3)
    if hbm_roof:
        out["measured_hbm_roof_tbps"] = hbm_roof
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bench_10m.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
