"""Measured-bandwidth microbenchmarks for the roofline report.

PERF_NOTES' roofline previously divided the wave kernel's modeled streamed
volume by the v5e's NOMINAL ~2 TB/s VMEM figure, which produced
`est_vmem_bw_frac: 1.38` — a >1.0 "fraction" that only proves the model
or the nominal roof is off.  This tool measures the roofs this chip
actually delivers:

* hbm_stream_tbps — big out-of-place elementwise op over an HBM-resident
  array (reads + writes counted), the classic stream test.
* vmem_stream_tbps — a Pallas kernel whose grid re-reads the SAME
  VMEM-resident block every step and accumulates it; after the first
  step the block never leaves VMEM, so the sustained rate is VMEM read
  bandwidth as Mosaic schedules it (including the per-step VPU add).

Timings force a host transfer of one scalar — on the remote-TPU runtime
`block_until_ready` can return early (PERF_NOTES), so every measurement
here ends in float(...).

Writes docs/bandwidth.json; tools/bench_10m.py divides its volume model
by these measured roofs.
"""
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _time(fn, *args, reps=3):
    """fn must return a SCALAR (the device-loop pattern of
    tools/profile_hl.py: reduce on device, pull one float — pulling whole
    arrays rides the ~30MB/s tunnel and block_until_ready lies)."""
    float(fn(*args))                # compile + first-run autotune
    best = float("inf")
    for _ in range(reps):
        t = time.time()
        _ = float(fn(*args))
        best = min(best, time.time() - t)
    return best


def hbm_stream(jax, jnp, nbytes=1 << 29, steps=256):
    n = nbytes // 4
    x = jnp.ones((n,), jnp.float32)

    @jax.jit
    def loop(a):
        def step(c, i):
            c = c * 1.0000001 + i   # carried: every step re-streams HBM
            return c, None
        out, _ = jax.lax.scan(step, a,
                              jnp.arange(steps, dtype=jnp.float32))
        return jnp.sum(out[:8])

    t = _time(loop, x)
    return 2.0 * nbytes * steps / t / 1e12   # read + write per step


def vmem_stream(jax, jnp, steps=1 << 19, rows=512, lanes=2048):
    """Accumulate the same [rows, lanes] bf16 block `steps` times; the
    block (2MB) stays VMEM-resident across grid steps (constant
    index_map), so steady-state traffic is VMEM reads."""
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            o_ref[...] = jnp.zeros_like(o_ref)
        o_ref[...] += x_ref[...].astype(jnp.float32)

    x = jnp.ones((rows, lanes), jnp.bfloat16)
    call = pl.pallas_call(
        kernel, grid=(steps,),
        in_specs=[pl.BlockSpec((rows, lanes), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((rows, lanes), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, lanes), jnp.float32))
    f = jax.jit(lambda a: jnp.sum(call(a)[:2, :8]))
    t = _time(f, x)
    return steps * rows * lanes * 2 / t / 1e12


def main():
    import jax
    import jax.numpy as jnp
    out = {
        "hbm_stream_tbps": round(hbm_stream(jax, jnp), 3),
        "vmem_stream_tbps": round(vmem_stream(jax, jnp), 3),
        "backend": jax.default_backend(),
        "measured_at": time.strftime("%Y-%m-%d"),
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bandwidth.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
