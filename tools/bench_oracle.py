"""Measure the REAL reference LightGBM CLI on the exact bench dataset.

Trains the oracle binary (tools/build_reference_oracle.sh) on the same
Higgs-like synthetic that bench.py uses (same generator, same seed, same
params: 255 leaves, max_bin 255, lr 0.1, min_data_in_leaf 20), times
sec/iter as (t(ITERS_HI) - t(ITERS_LO)) / (ITERS_HI - ITERS_LO) so data
loading/binning is excluded, computes held-out AUC with the same
tie-averaged AUC as bench.py, and writes docs/oracle_bench.json, which
bench.py folds into its output as ref_auc / ref_sec_per_iter /
vs_ref_measured.

Run manually once per host class: the result records host facts
(cpu count, model) so the judged numbers carry their context.
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import FEATURES, NUM_LEAVES, ROWS, _auc, make_higgs_like

ORACLE = "/tmp/lgb_ref_src/lightgbm"
ITERS_LO = 13
ITERS_HI = 63


def main():
    if not os.path.exists(ORACLE):
        print("oracle binary missing; run tools/build_reference_oracle.sh",
              file=sys.stderr)
        return 1
    work = tempfile.mkdtemp(prefix="lgb_oracle_bench")
    try:
        return _run(work)
    finally:
        shutil.rmtree(work, ignore_errors=True)


def _run(work):
    X, y = make_higgs_like(ROWS, FEATURES)
    Xte, yte = make_higgs_like(100_000, FEATURES, seed=1)
    train_csv = os.path.join(work, "train.csv")
    test_csv = os.path.join(work, "test.csv")
    np.savetxt(train_csv, np.column_stack([y, X]), fmt="%.9g", delimiter="\t")
    np.savetxt(test_csv, np.column_stack([yte, Xte]), fmt="%.9g",
               delimiter="\t")

    def train(iters, model_out):
        conf = os.path.join(work, f"train_{iters}.conf")
        with open(conf, "w") as f:
            f.write(f"""task = train
objective = binary
data = {train_csv}
num_trees = {iters}
num_leaves = {NUM_LEAVES}
max_bin = 255
learning_rate = 0.1
min_data_in_leaf = 20
metric = none
verbosity = -1
output_model = {model_out}
""")
        t0 = time.time()
        subprocess.run([ORACLE, f"config={conf}"], check=True,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        return time.time() - t0

    model_lo = os.path.join(work, "m_lo.txt")
    t_lo = train(ITERS_LO, model_lo)
    model_hi = os.path.join(work, "m_hi.txt")
    t_hi = train(ITERS_HI, model_hi)
    sec_per_iter = (t_hi - t_lo) / (ITERS_HI - ITERS_LO)

    # held-out AUC at ITERS_LO iterations = the same trained-iteration
    # count as bench.py's quality gate (3 warmup + 10 timed)
    pred_out = os.path.join(work, "pred.txt")
    pconf = os.path.join(work, "pred.conf")
    with open(pconf, "w") as f:
        f.write(f"""task = predict
data = {test_csv}
input_model = {model_lo}
output_result = {pred_out}
""")
    subprocess.run([ORACLE, f"config={pconf}"], check=True,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    auc = _auc(yte, np.loadtxt(pred_out))

    cpu_model = ""
    try:
        for line in open("/proc/cpuinfo"):
            if line.startswith("model name"):
                cpu_model = line.split(":", 1)[1].strip()
                break
    except OSError:
        pass
    out = {
        "rows": ROWS,
        "num_leaves": NUM_LEAVES,
        "iters_lo": ITERS_LO,
        "iters_timed": ITERS_HI - ITERS_LO,
        "ref_sec_per_iter": round(sec_per_iter, 4),
        "ref_auc_at_iters_lo": round(auc, 5),
        "wall_lo": round(t_lo, 2),
        "wall_hi": round(t_hi, 2),
        "host_cpus": os.cpu_count(),
        "host_cpu_model": cpu_model,
        "note": ("reference CLI measured on THIS host (single benchmark "
                 "process, OpenMP over all host cores); compare with the "
                 "docs-scaled 28-core 2015 anchor in BASELINE.md"),
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "oracle_bench.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
