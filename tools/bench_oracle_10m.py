"""Reference oracle CLI on the 10M BASELINE workload (single host core):
same data/params as tools/bench_10m.py, timing excludes load/binning by
differencing two runs (13 vs 63 trees), AUC at 103 trees matches the TPU
run's 3 warmup + 100 timed.  Writes docs/oracle_bench_10m.json."""
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import FEATURES, _auc, make_higgs_like
from tools.bench_10m import ROWS, TEST_ROWS

ORACLE = "/tmp/lgb_ref_src/lightgbm"
ITERS_LO = 13
ITERS_HI = 63
ITERS_AUC = 103


def write_tsv(path, y, X):
    # np.savetxt is ~10x too slow at 10M rows on one core; format in
    # chunks with a preallocated %.7g vectorized formatter
    with open(path, "w") as f:
        step = 200_000
        for i in range(0, len(y), step):
            block = np.column_stack([y[i:i + step], X[i:i + step]])
            lines = "\n".join(
                "\t".join(f"{v:.7g}" for v in row) for row in block)
            f.write(lines + "\n")


def main():
    if not os.path.exists(ORACLE):
        print("oracle binary missing; run tools/build_reference_oracle.sh",
              file=sys.stderr)
        return 1
    work = tempfile.mkdtemp(prefix="lgb_oracle_10m")
    try:
        X, y = make_higgs_like(ROWS, FEATURES)
        Xte, yte = make_higgs_like(TEST_ROWS, FEATURES, seed=1)
        train_tsv = os.path.join(work, "train.tsv")
        test_tsv = os.path.join(work, "test.tsv")
        t0 = time.time()
        write_tsv(train_tsv, y, X)
        write_tsv(test_tsv, yte, Xte)
        print(f"tsv written in {time.time()-t0:.0f}s", flush=True)

        def train(iters, model_out):
            conf = os.path.join(work, f"train_{iters}.conf")
            with open(conf, "w") as f:
                f.write(f"""task = train
objective = binary
data = {train_tsv}
output_model = {model_out}
num_trees = {iters}
num_leaves = 255
max_bin = 255
learning_rate = 0.1
min_data_in_leaf = 20
num_threads = 1
verbosity = -1
label_column = 0
""")
            t0 = time.time()
            subprocess.run([ORACLE, f"config={conf}"], check=True,
                           stdout=subprocess.DEVNULL)
            return time.time() - t0

        t_lo = train(ITERS_LO, os.path.join(work, "m_lo.txt"))
        print(f"{ITERS_LO} trees: {t_lo:.0f}s", flush=True)
        t_hi = train(ITERS_HI, os.path.join(work, "m_hi.txt"))
        print(f"{ITERS_HI} trees: {t_hi:.0f}s", flush=True)
        t_auc = train(ITERS_AUC, os.path.join(work, "m_auc.txt"))
        print(f"{ITERS_AUC} trees: {t_auc:.0f}s", flush=True)
        pred = os.path.join(work, "pred.txt")
        conf = os.path.join(work, "pred.conf")
        with open(conf, "w") as f:
            f.write(f"""task = predict
data = {test_tsv}
input_model = {os.path.join(work, 'm_auc.txt')}
output_result = {pred}
label_column = 0
""")
        subprocess.run([ORACLE, f"config={conf}"], check=True,
                       stdout=subprocess.DEVNULL)
        scores = np.loadtxt(pred)
        auc = _auc(yte, scores)
        out = {"rows": ROWS, "num_leaves": 255,
               "ref_sec_per_iter": round((t_hi - t_lo)
                                         / (ITERS_HI - ITERS_LO), 4),
               "iters_auc": ITERS_AUC,
               "ref_auc_at_iters": round(float(auc), 5),
               "host_cpus": os.cpu_count(),
               "measured_at": time.strftime("%Y-%m-%d")}
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "docs", "oracle_bench_10m.json")
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps(out))
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
