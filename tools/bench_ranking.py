"""Device vs host lambdarank gradient step on MSLR-like shapes
(VERDICT r3 item 6: >=5x gradient-step speedup at ~100k docs).

Times ONLY the gradient computation: host = the per-query numpy loop
(ranking.py RankingObjective.get_gradients_host), device = the bucketed
pairwise program (LambdarankNDCG.make_device_grad_fn) with a host
transfer as the completion barrier (block_until_ready can return early
through the axon tunnel)."""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N_DOCS = int(os.environ.get("RANKBENCH_DOCS", 100_000))
REPS = 10


def main():
    import jax.numpy as jnp

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Metadata
    from lightgbm_tpu.ranking import LambdarankNDCG

    rng = np.random.RandomState(0)
    # MSLR-WEB30K-like query-length mix (mean ~120 docs, long tail)
    lens = []
    total = 0
    while total < N_DOCS:
        ln = int(np.clip(rng.lognormal(4.2, 0.8), 1, 1200))
        lens.append(ln)
        total += ln
    lens[-1] -= total - N_DOCS
    if lens[-1] <= 0:
        lens.pop()
    n = sum(lens)
    labels = rng.randint(0, 5, n).astype(np.float64)
    md = Metadata(n)
    md.set_label(labels)
    md.set_group(np.asarray(lens, np.int64))
    obj = LambdarankNDCG(Config({"objective": "lambdarank"}))
    obj.init(md, n)
    score = rng.randn(n)

    t0 = time.time()
    for _ in range(3):
        obj.get_gradients_host(score)
    host_s = (time.time() - t0) / 3

    n_pad = (n + 1023) // 1024 * 1024
    fn = obj.make_device_grad_fn(n_pad)
    sc = jnp.zeros((1, n_pad)).at[0, :n].set(
        jnp.asarray(score, jnp.float32))
    g, h = fn(sc, None)
    _ = np.asarray(g)  # compile + settle
    t0 = time.time()
    for _ in range(REPS):
        g, h = fn(sc, None)
    _ = np.asarray(g) + np.asarray(h)  # completion barrier
    dev_s = (time.time() - t0) / REPS

    # rank_xendcg: same shapes, same harness (device program added in
    # round 5; ref cuda_rank_objective.cu:385-624)
    from lightgbm_tpu.ranking import RankXENDCG
    xobj = RankXENDCG(Config({"objective": "rank_xendcg"}))
    xobj.init(md, n)
    t0 = time.time()
    for _ in range(3):
        xobj.get_gradients_host(score)
    xe_host_s = (time.time() - t0) / 3
    xfn = xobj.make_device_grad_fn(n_pad)
    g, h = xfn(sc, None)
    _ = np.asarray(g)
    t0 = time.time()
    for _ in range(REPS):
        g, h = xfn(sc, None)
    _ = np.asarray(g) + np.asarray(h)
    xe_dev_s = (time.time() - t0) / REPS

    out = {"docs": n, "queries": len(lens),
           "host_grad_s": round(host_s, 4),
           "device_grad_s": round(dev_s, 4),
           "speedup": round(host_s / dev_s, 2),
           "xendcg_host_grad_s": round(xe_host_s, 4),
           "xendcg_device_grad_s": round(xe_dev_s, 4),
           "xendcg_speedup": round(xe_host_s / xe_dev_s, 2)}
    print(json.dumps(out))
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "bench_ranking.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
