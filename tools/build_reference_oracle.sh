#!/bin/bash
# Build the reference LightGBM CLI as a parity-test oracle in /tmp.
#
# The reference tree at /root/reference is read-only and its vendored
# submodules (fmt, fast_double_parser, eigen) are empty, so this script
# clones it to /tmp, installs two tiny stub headers (strtod / snprintf
# shims), drops the Eigen-dependent linear tree learner, and builds the
# CPU CLI.  tests/test_reference_parity.py skips unless the binary exists.
set -euo pipefail

SRC=${1:-/root/reference}
WORK=/tmp/lgb_ref_src
BUILD=/tmp/lgb_ref_build

[ -x "$WORK/lightgbm" ] && { echo "oracle already built: $WORK/lightgbm"; exit 0; }

rm -rf "$WORK" "$BUILD"
cp -r "$SRC" "$WORK"
sed -i 's/cmake_minimum_required(VERSION 3.28)/cmake_minimum_required(VERSION 3.18)/' "$WORK/CMakeLists.txt"
sed -i 's|      src/treelearner/linear_tree_learner.cpp||' "$WORK/CMakeLists.txt"
sed -i 's|#include "linear_tree_learner.h"||' "$WORK/src/treelearner/tree_learner.cpp"
sed -i 's|        return new LinearTreeLearner(config);|        Log::Fatal("linear tree disabled in oracle build");|' "$WORK/src/treelearner/tree_learner.cpp"

mkdir -p "$WORK/external_libs/fast_double_parser/include" \
         "$WORK/external_libs/fmt/include/fmt"

cat > "$WORK/external_libs/fast_double_parser/include/fast_double_parser.h" <<'EOF'
// strtod shim for the absent vendored fast_double_parser (oracle build only)
#pragma once
#include <cstdlib>
namespace fast_double_parser {
inline const char* parse_number(const char* p, double* out) {
  char* end;
  *out = std::strtod(p, &end);
  if (end == p) return nullptr;
  return end;
}
}
EOF

cat > "$WORK/external_libs/fmt/include/fmt/format.h" <<'EOF'
// snprintf shim for the absent vendored {fmt} (oracle build only); covers
// the three format strings common.h uses: "{}", "{:g}", "{:.17g}"
#pragma once
#include <cstdio>
#include <cstring>
#include <type_traits>
namespace fmt {
template <typename OutIt> struct format_to_n_result { OutIt out; size_t size; };
template <typename T>
inline format_to_n_result<char*> format_to_n(char* buf, size_t n,
                                             const char* f, T value) {
  int len;
  if (std::strcmp(f, "{:.17g}") == 0)
    len = snprintf(buf, n, "%.17g", (double)value);
  else if (std::strcmp(f, "{:g}") == 0)
    len = snprintf(buf, n, "%g", (double)value);
  else if (std::is_floating_point<T>::value)
    len = snprintf(buf, n, "%g", (double)value);
  else if (std::is_signed<T>::value)
    len = snprintf(buf, n, "%lld", (long long)value);
  else
    len = snprintf(buf, n, "%llu", (unsigned long long)value);
  size_t l = (size_t)(len < 0 ? 0 : len);
  return {buf + (l < n ? l : n), l};
}
}
EOF

cmake -S "$WORK" -B "$BUILD" -DCMAKE_BUILD_TYPE=Release -DUSE_OPENMP=ON
cmake --build "$BUILD" --target lightgbm -j "$(nproc)"
echo "oracle built: $WORK/lightgbm"
