#!/usr/bin/env python
"""Event-schema sync gate: emitted event types <-> docs table.

Config-doc-sync's sibling (tools/gen_params_doc.py --check): the
structured event log is an interface — bench.py, the distributed
supervisor, the flight recorder and any fleet tooling key on `event`
names — so every event type the package can emit must appear in
docs/Observability.md's event-type reference table, and every table row
must correspond to a real emitter (no stale rows).

Discovery is syntactic: any call of `emit_event(...)`,
`emit_event_sync(...)`, `<logger>.emit(...)` or `<logger>.emit_sync(...)`
whose first argument is a string literal inside lightgbm_tpu/.  The doc
side is the table between the `<!-- event-table:begin -->` and
`<!-- event-table:end -->` markers; the first cell of each row lists
one or more backticked event names.

Usage: python tools/check_event_docs.py   # exit 1 on drift
"""

import ast
import os
import re
import sys

REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))
PKG = os.path.join(REPO, "lightgbm_tpu")
DOC = os.path.join(REPO, "docs", "Observability.md")

EMIT_NAMES = {"emit_event", "emit_event_sync", "emit", "emit_sync"}


def emitted_events():
    found = {}
    for root, _dirs, files in os.walk(PKG):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            try:
                tree = ast.parse(open(path).read())
            except SyntaxError as e:
                print(f"check_event_docs: cannot parse {path}: {e}")
                return None
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                fn = node.func
                name = (fn.id if isinstance(fn, ast.Name)
                        else fn.attr if isinstance(fn, ast.Attribute)
                        else None)
                if name not in EMIT_NAMES:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    rel = os.path.relpath(path, REPO)
                    found.setdefault(arg.value, f"{rel}:{node.lineno}")
    return found


def documented_events():
    try:
        text = open(DOC).read()
    except OSError as e:
        print(f"check_event_docs: cannot read {DOC}: {e}")
        return None
    m = re.search(r"<!-- event-table:begin -->(.*?)"
                  r"<!-- event-table:end -->", text, re.S)
    if not m:
        print(f"check_event_docs: {DOC} has no "
              "<!-- event-table:begin/end --> markers")
        return None
    names = set()
    for line in m.group(1).splitlines():
        line = line.strip()
        if not line.startswith("|"):
            continue
        first_cell = line.split("|")[1]
        names.update(re.findall(r"`([A-Za-z0-9_]+)`", first_cell))
    names.discard("event")  # the header row
    return names


def main() -> int:
    emitted = emitted_events()
    documented = documented_events()
    if emitted is None or documented is None:
        return 1
    missing = sorted(set(emitted) - documented)
    stale = sorted(documented - set(emitted))
    ok = True
    if missing:
        ok = False
        print("events emitted but missing from docs/Observability.md's "
              "event table:")
        for name in missing:
            print(f"  {name}  (first emitter: {emitted[name]})")
    if stale:
        ok = False
        print("events documented but never emitted (stale rows):")
        for name in stale:
            print(f"  {name}")
    if ok:
        print(f"event table is in sync ({len(emitted)} event types)")
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
