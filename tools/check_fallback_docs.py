#!/usr/bin/env python
"""Fallback-matrix sync gate: host-fallback branches <-> docs table.

check_event_docs.py's sibling for the inference router: the ROADMAP's
"kill the host-fallback matrix" item only works if the matrix is TRUE —
a production daemon quietly serving requests at Python speed because of
an undocumented fallback is exactly the regression this gate blocks.
Every host-fallback decision in the device-predict router calls
`_host_fallback("<key>")` (gbdt._device_predictor, inference/pack.py),
and docs/Inference.md's fallback matrix lists one row per key between
the `<!-- fallback-matrix:begin/end -->` markers.  Both directions are
enforced: an undocumented call-site key fails, and a documented key
with no call site (a fallback that was CLOSED — the end state the
ROADMAP wants) fails as stale until the row is removed.

Discovery is syntactic, like the event gate: any call of
`_host_fallback(...)` (name or attribute form) whose first argument is
a string literal inside lightgbm_tpu/.

Usage: python tools/check_fallback_docs.py   # exit 1 on drift
"""

import ast
import os
import re
import sys

REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))
PKG = os.path.join(REPO, "lightgbm_tpu")
DOC = os.path.join(REPO, "docs", "Inference.md")

FALLBACK_NAMES = {"_host_fallback"}


def code_fallbacks():
    found = {}
    for root, _dirs, files in os.walk(PKG):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            try:
                tree = ast.parse(open(path).read())
            except SyntaxError as e:
                print(f"check_fallback_docs: cannot parse {path}: {e}")
                return None
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                fn = node.func
                name = (fn.id if isinstance(fn, ast.Name)
                        else fn.attr if isinstance(fn, ast.Attribute)
                        else None)
                if name not in FALLBACK_NAMES:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    rel = os.path.relpath(path, REPO)
                    found.setdefault(arg.value, f"{rel}:{node.lineno}")
    return found


def documented_fallbacks():
    try:
        text = open(DOC).read()
    except OSError as e:
        print(f"check_fallback_docs: cannot read {DOC}: {e}")
        return None
    m = re.search(r"<!-- fallback-matrix:begin -->(.*?)"
                  r"<!-- fallback-matrix:end -->", text, re.S)
    if not m:
        print(f"check_fallback_docs: {DOC} has no "
              "<!-- fallback-matrix:begin/end --> markers")
        return None
    keys = set()
    for line in m.group(1).splitlines():
        line = line.strip()
        if not line.startswith("|"):
            continue
        first_cell = line.split("|")[1]
        keys.update(re.findall(r"`([A-Za-z0-9_\-]+)`", first_cell))
    keys.discard("key")  # the header row
    return keys


def main() -> int:
    in_code = code_fallbacks()
    in_docs = documented_fallbacks()
    if in_code is None or in_docs is None:
        return 1
    missing = sorted(set(in_code) - in_docs)
    stale = sorted(in_docs - set(in_code))
    ok = True
    if missing:
        ok = False
        print("host fallbacks in code but missing from "
              "docs/Inference.md's fallback matrix:")
        for key in missing:
            print(f"  {key}  (call site: {in_code[key]})")
    if stale:
        ok = False
        print("fallback rows documented but with no _host_fallback call "
              "site (fallback closed? remove the row):")
        for key in stale:
            print(f"  {key}")
    if ok:
        print(f"fallback matrix is in sync ({len(in_code)} fallback "
              "key(s))")
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
