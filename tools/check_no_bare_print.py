#!/usr/bin/env python
"""Lint: all runtime output must go through `lightgbm_tpu.utils.log` (or
the structured event log, observability/events.py), never bare print().

A bare print() in library code bypasses verbosity gating, the
register_logger/register_callback redirection that the sklearn wrapper
and embedding applications rely on, and the rank-tagged event log —
under multi-process SPMD it also interleaves unsynchronized worker
output.  The reference enforces the same discipline with its Log::
macros (include/LightGBM/utils/log.h).

Scope: every .py under lightgbm_tpu/ (the runtime package).  Entry-point
scripts outside the package (bench.py, tools/, examples/) print their
results by design and are exempt.  Whitelist inside the package:

* utils/log.py           — print() IS the default stderr sink
* sys.stderr.write(...)  — not flagged (used by the crash-injection
  marker in reliability/faults.py, which must bypass any registered
  logger right before os._exit)

Usage: python tools/check_no_bare_print.py [package_dir]
Exit 1 when violations are found (wired into tier-1 via
tests/test_no_bare_print.py).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

WHITELIST = {
    os.path.join("lightgbm_tpu", "utils", "log.py"),
}


def find_bare_prints(package_dir: str) -> List[Tuple[str, int]]:
    """(relative_path, lineno) of every bare print() call under
    `package_dir`, whitelist applied."""
    root = os.path.dirname(os.path.abspath(package_dir))
    violations: List[Tuple[str, int]] = []
    for dirpath, _dirnames, filenames in os.walk(package_dir):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root)
            if rel in WHITELIST:
                continue
            with open(path) as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError as e:
                    violations.append((rel, e.lineno or 0))
                    continue
            for node in ast.walk(tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "print"):
                    violations.append((rel, node.lineno))
    return violations


def main(argv: List[str]) -> int:
    package_dir = (argv[1] if len(argv) > 1 else
                   os.path.join(os.path.dirname(
                       os.path.dirname(os.path.abspath(__file__))),
                       "lightgbm_tpu"))
    violations = find_bare_prints(package_dir)
    for rel, lineno in violations:
        print(f"{rel}:{lineno}: bare print() — route output through "
              "utils.log or the event log")
    if violations:
        print(f"{len(violations)} bare print() call(s) found")
        return 1
    print("OK: no bare print() calls in the runtime package")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
