"""Multi-chip scaling evidence (VERDICT round-2 item 6): compile the
data-parallel wave training step over virtual CPU meshes of 1/2/4/8
devices, count the all-reduce collectives and their byte volumes from the
compiled HLO, time a step at each mesh size, and print the ICI-cost
projection for a v5e-8 slice.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python tools/collective_accounting.py
"""
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np

N = 1 << 14
F = 8
B = 64
L = 31


def all_reduce_stats(hlo_text):
    """(count, total bytes) of all-reduce results in compiled HLO: scan
    lines whose op is all-reduce(-start) and sum their RESULT shapes."""
    total_bytes = 0
    count = 0
    sz = {"f32": 4, "s32": 4, "u32": 4, "bf16": 2, "f64": 8,
          "s64": 8, "u8": 1, "s8": 1, "pred": 1}
    for line in hlo_text.splitlines():
        if ("all-reduce(" not in line and "all-reduce-start(" not in line) \
                or "=" not in line:
            continue
        # result shape sits between "= " and the op name (the op NAME
        # itself contains "all-reduce", so split after the "=")
        lhs = line.split(" = ", 1)[1].split("all-reduce")[0]
        shapes = re.findall(r"(f32|s32|bf16|f64|s64|u32|u8|s8|pred)"
                            r"\[([\d,]*)\]", lhs)
        for dt, dims in shapes:
            elems = 1
            for d in dims.split(","):
                if d:
                    elems *= int(d)
            total_bytes += elems * sz[dt]
        count += 1
    return count, total_bytes


def main():
    import jax

    # the axon TPU plugin ignores JAX_PLATFORMS; force the CPU backend
    jax.config.update("jax_platforms", "cpu")

    import lightgbm_tpu as lgb

    rng = np.random.RandomState(0)
    X = rng.rand(N, F).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + 0.1 * rng.randn(N) > 0.7).astype(np.float64)

    results = {}
    for ndev in (1, 2, 4, 8):
        params = {"objective": "binary", "num_leaves": L, "max_bin": B,
                  "verbosity": -1, "metric": "none",
                  "tree_learner": "data", "num_machines": ndev,
                  "tpu_growth_strategy": "wave", "hist_method": "segment"}
        b = lgb.Booster(params=params,
                        train_set=lgb.Dataset(X, label=y))
        t0 = time.time()
        b.update()                      # compile + first step
        compile_s = time.time() - t0
        t0 = time.time()
        for _ in range(3):
            b.update()
        _ = np.asarray(b._gbdt.scores[0][:4])
        step_s = (time.time() - t0) / 3
        mesh = b._gbdt.mesh
        results[ndev] = {"step_s": step_s, "compile_s": compile_s,
                         "mesh": None if mesh is None
                         else tuple(mesh.devices.shape)}
        print(f"ndev={ndev}: step {step_s*1e3:8.1f} ms "
              f"(compile {compile_s:.1f}s, mesh "
              f"{results[ndev]['mesh']})", flush=True)

    # collective accounting from the compiled HLO of the tree builder
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from lightgbm_tpu.learner import FeatureMeta, GrowParams, grow_tree_wave
    from lightgbm_tpu.ops.split import SplitParams
    import jax.numpy as jnp
    devs = np.array(jax.devices()[:8]).reshape(8)
    mesh = Mesh(devs, ("row",))
    shard = NamedSharding(mesh, P(None, "row"))
    repl = NamedSharding(mesh, P())
    rowsh = NamedSharding(mesh, P("row"))
    binned = jax.device_put(
        rng.randint(0, B, size=(F, N)).astype(np.uint8), shard)
    grad = jax.device_put(rng.randn(N).astype(np.float32), rowsh)
    hess = jax.device_put(np.abs(rng.rand(N).astype(np.float32)) + 0.1,
                          rowsh)
    mask = jax.device_put(np.ones(N, np.float32), rowsh)
    cmask = jax.device_put(np.ones(F, bool), repl)
    meta = FeatureMeta(
        num_bin=jax.device_put(np.full(F, B, np.int32), repl),
        missing_type=jax.device_put(np.zeros(F, np.int32), repl),
        default_bin=jax.device_put(np.zeros(F, np.int32), repl),
        penalty=jax.device_put(np.ones(F, np.float32), repl))
    gp = GrowParams(num_leaves=L, max_bin=B, hist_method="segment",
                    split=SplitParams(min_data_in_leaf=20))
    lowered = jax.jit(grow_tree_wave, static_argnames=("params",)).lower(
        binned, grad, hess, mask, cmask, meta, gp)
    hlo = lowered.compile().as_text()
    n_ar, bytes_ar = all_reduce_stats(hlo)
    print(f"grow_tree_wave over 8-device row mesh: {n_ar} all-reduce ops, "
          f"{bytes_ar/1e6:.2f} MB reduced per tree", flush=True)

    # ICI projection at bench scale (v5e-8, 45 GB/s per link, ring
    # all-reduce 2(p-1)/p factor)
    F_b, B_b, L_b = 28, 256, 255
    kbs = [8, 8, 8, 8, 8, 16, 32, 64]      # ladder Kb with subtraction
    bytes_per_iter = sum(k * F_b * B_b * 2 * 4 for k in kbs)
    ici = bytes_per_iter * 2 * 7 / 8 / 45e9
    print(f"bench-scale projection: {bytes_per_iter/1e6:.1f} MB of "
          f"histogram psum per iter -> ~{ici*1e3:.2f} ms over v5e-8 ICI "
          f"(vs 145 ms single-chip compute)", flush=True)
    return results, n_ar, bytes_ar


if __name__ == "__main__":
    main()
