"""Scale demo for CSC-direct sparse ingestion (VERDICT round-2 item 2):
1M rows x 5000 features at ~0.5% density — a news20/Criteo-shaped mix of
one-hot indicator blocks (EFB-compressible) and continuous sparse
columns — ingested and trained WITHOUT ever materializing the 40 GB
dense [n, F] float64 matrix.  Prints peak RSS and timings."""
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import scipy.sparse as sp


def rss_gb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def main():
    n = 1_000_000
    n_blocks, block = 45, 100          # 4500 one-hot indicator features
    n_cont = 500                       # continuous sparse tail
    rng = np.random.RandomState(0)

    hot = rng.randint(0, block, size=(n, n_blocks))
    oh_cols = (hot + np.arange(n_blocks)[None, :] * block).ravel()
    oh_rows = np.repeat(np.arange(n), n_blocks)

    nnz_c = int(n * n_cont * 0.005)
    c_rows = rng.randint(0, n, size=nnz_c)
    c_cols = n_blocks * block + rng.randint(0, n_cont, size=nnz_c)
    c_vals = rng.randn(nnz_c).astype(np.float64)

    F = n_blocks * block + n_cont
    m = sp.csr_matrix(
        (np.concatenate([np.ones(len(oh_rows)), c_vals]),
         (np.concatenate([oh_rows, c_rows]),
          np.concatenate([oh_cols, c_cols]))), shape=(n, F))
    y = ((hot[:, 0] % 2 == 0) ^ (rng.rand(n) < 0.2)).astype(np.float64)
    print(f"data: {n}x{F}, nnz={m.nnz} "
          f"(density {m.nnz/(n*F):.4f}), rss={rss_gb():.2f} GB", flush=True)

    import lightgbm_tpu as lgb
    t0 = time.time()
    ds = lgb.Dataset(m, label=y)
    ds._core_or_construct()
    cols = ds._core.binned.shape[0]
    print(f"ingest: {time.time()-t0:.1f}s -> {cols} bundle columns, "
          f"rss={rss_gb():.2f} GB", flush=True)

    t0 = time.time()
    b = lgb.train({"objective": "binary", "num_leaves": 31,
                   "verbosity": -1, "metric": "none"}, ds,
                  num_boost_round=10)
    print(f"train 10 iters: {time.time()-t0:.1f}s, rss={rss_gb():.2f} GB",
          flush=True)
    pred = b.predict(m[:100_000])
    acc = float(np.mean((pred > 0.5) == (y[:100_000] > 0.5)))
    print(f"train-subset accuracy: {acc:.4f} (label noise 0.2 -> "
          f"ceiling 0.8), rss={rss_gb():.2f} GB", flush=True)


if __name__ == "__main__":
    main()
