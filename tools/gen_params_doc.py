#!/usr/bin/env python
"""Generate docs/Parameters.md from the single-definition PARAMS table.

Mirrors the reference's parameter-generator pipeline (ref:
.ci/parameter-generator.py, which renders docs/Parameters.rst and
src/io/config_auto.cpp from config.h doc-comments): one source of truth
(lightgbm_tpu/config.py PARAMS) renders the user-facing doc, so the doc
can never drift from the accepted parameters.

Usage: python tools/gen_params_doc.py [--check]
  --check  exit 1 if docs/Parameters.md is stale (CI guard)
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from lightgbm_tpu.config import PARAMS  # noqa: E402

HEADER = """# Parameters

Auto-generated from `lightgbm_tpu/config.py` (`PARAMS`) by
`tools/gen_params_doc.py` — edit the table there, not this file.

Semantics follow the reference (LightGBM `docs/Parameters.rst`): the
first occurrence of a parameter or any of its aliases wins; aliases
normalize to the canonical name; unknown parameters warn.

| Parameter | Type | Default | Aliases |
|---|---|---|---|
"""


def render() -> str:
    rows = []
    for name, typ, default, aliases in PARAMS:
        d = repr(default) if default != "" else '""'
        a = ", ".join(aliases) if aliases else "—"
        rows.append(f"| `{name}` | {typ} | `{d}` | {a} |")
    return HEADER + "\n".join(rows) + "\n"


def main() -> int:
    out_path = os.path.join(os.path.dirname(__file__), "..", "docs",
                            "Parameters.md")
    out_path = os.path.normpath(out_path)
    text = render()
    if "--check" in sys.argv:
        if not os.path.exists(out_path) or open(out_path).read() != text:
            print("docs/Parameters.md is stale; run tools/gen_params_doc.py")
            return 1
        print("docs/Parameters.md is up to date")
        return 0
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        f.write(text)
    print(f"wrote {out_path} ({len(PARAMS)} parameters)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
