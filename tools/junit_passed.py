#!/usr/bin/env python
"""Count tier-1 PASSES from pytest's --junitxml report.

`tools/verify.sh` used to derive DOTS_PASSED by grepping the dot stream
(`^[.FEsx]+` lines) out of the captured log — which miscounts whenever
an ORPHANED pytest process (a previous run's survivor, a test-spawned
subprocess inheriting stdout) interleaves ITS dots into the same
terminal capture (observed container quirk).  The junit XML is written
by exactly one pytest process to exactly one file, so the count cannot
be polluted by a stranger's output.

Usage: python tools/junit_passed.py REPORT.xml [LOG]

Prints a single integer.  A testcase counts as passed when it carries
no <failure>/<error>/<skipped> child.  When the XML is missing or
unparseable (the 870 s timeout can kill pytest before it writes the
report), falls back to the legacy dot-stream grep over LOG when given,
else prints 0 — never crashes, the gate needs a number.
"""

from __future__ import annotations

import re
import sys
import xml.etree.ElementTree as ET


def count_junit(path: str) -> int:
    tree = ET.parse(path)
    passed = 0
    for case in tree.getroot().iter("testcase"):
        if any(child.tag in ("failure", "error", "skipped")
               for child in case):
            continue
        passed += 1
    return passed


def count_dots(log_path: str) -> int:
    """Legacy fallback: dots in progress lines of a -q pytest log."""
    dot_line = re.compile(r"^[.FEsx]+( *\[ *[0-9]+%\])?$")
    n = 0
    with open(log_path, "rb") as f:
        for raw in f:
            line = raw.decode("utf-8", "replace").rstrip("\n")
            if dot_line.match(line):
                n += line.count(".")
    return n


def main(argv) -> int:
    if not argv:
        sys.stderr.write(__doc__)
        return 2
    try:
        sys.stdout.write(f"{count_junit(argv[0])}\n")
        return 0
    except Exception:
        pass
    if len(argv) > 1:
        try:
            sys.stdout.write(f"{count_dots(argv[1])}\n")
            return 0
        except Exception:
            pass
    sys.stdout.write("0\n")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
