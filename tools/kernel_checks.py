"""On-chip Pallas kernel correctness gate, run by bench.py every round.

The 7 kernel unit tests skip off-TPU, so without this gate a Mosaic/XLA
regression in the histogram kernels would surface only as an unexplained
AUC delta in the next BENCH json (round-4 verdict, weak #6).  bench.py
calls run_checks() on the real chip and carries a pass/fail field in the
driver JSON line — the TPU counterpart of the reference's dual-gate CI
(.ci scripts running both CPU and CUDA test legs).

Checks (small shapes, seconds of chip time):
  1. fused wave kernel == XLA one-hot fallback (fp32, exact histograms)
  2. decomposed hi/lo kernel == full kernel at few computed slots
  3. int8 quantized kernel: exact int32 accumulation of grid-snapped
     gradients (dequantized result equals the fp32 kernel on grid values)
  4. single-leaf Pallas histogram == segment lowering
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mk(n=2048, F=8, B=64, slots=8, seed=0):
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    import ml_dtypes
    binned = rng.randint(0, B, size=(F, n)).astype(np.uint8)
    slot = rng.randint(0, slots, size=n).astype(np.int32)
    grad = rng.randn(n).astype(np.float32)
    hess = np.abs(rng.rand(n).astype(np.float32)) + 0.5
    mask = (rng.rand(n) < 0.9).astype(np.float32)
    gh = np.stack([grad * mask, hess * mask, mask], 1)
    # the kernels' MXU operands are bf16 (single-precision histograms,
    # like the reference GPU learner): snap inputs to the bf16 grid so
    # host fp64 ground truth and on-chip fp32 accumulation agree exactly
    gh = gh.astype(ml_dtypes.bfloat16).astype(np.float32)
    return (jnp.asarray(binned), jnp.asarray(slot), jnp.asarray(gh),
            binned, slot, gh)


def _host_hist(binned, slot, gh, B, slots):
    """NumPy ground truth [slots, F, B, C]."""
    F, n = binned.shape
    C = gh.shape[1] - 1
    out = np.zeros((slots, F, B, C), np.float64)
    cnt = np.zeros(slots, np.float64)
    for r in range(n):
        s = slot[r]
        if s >= slots:
            continue
        for f in range(F):
            out[s, f, binned[f, r], :] += gh[r, :C]
        cnt[s] += gh[r, C]
    return out, cnt


def run_checks():
    """Returns "ok" or "fail:<which>"."""
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import (build_histogram,
                                            build_histogram_rows_pallas,
                                            build_histogram_wave,
                                            build_histogram_wave_hl)
    failures = []
    B, slots = 64, 8
    binned, slot, gh, b_np, s_np, gh_np = _mk(B=B, slots=slots)
    want, want_cnt = _host_hist(b_np, s_np, gh_np, B, slots)

    # 1. fused wave kernel vs host ground truth (fp32 accumulates exactly
    #    at these magnitudes up to reduction-order ulps)
    try:
        h, cnt = build_histogram_wave(binned, slot, gh, max_bin=B,
                                      num_slots=slots)
        if not (np.allclose(np.asarray(h), want, rtol=1e-5, atol=1e-4)
                and np.allclose(np.asarray(cnt), want_cnt)):
            failures.append("wave_vs_host")
    except Exception as e:    # noqa: BLE001 - report, don't crash bench
        failures.append(f"wave_raised({type(e).__name__})")

    # 2. decomposed hi/lo kernel vs the full kernel (few computed slots)
    try:
        few = jnp.where(slot < 2, slot, slots)   # 2 computed slots
        hf, cf = build_histogram_wave(binned, few, gh, max_bin=B,
                                      num_slots=8)
        hd, cd = build_histogram_wave_hl(binned, binned.T, few, gh,
                                         max_bin=B, num_slots=2,
                                         out_slots=8)
        if not (np.allclose(np.asarray(hf)[:2], np.asarray(hd)[:2],
                            rtol=1e-5, atol=1e-4)
                and np.allclose(np.asarray(cf)[:2], np.asarray(cd)[:2])):
            failures.append("hl_vs_full")
    except Exception as e:
        failures.append(f"hl_raised({type(e).__name__})")

    # 3. int8 quantized kernel: grid-snapped grads accumulate EXACTLY
    try:
        qb = 16
        scales = np.array([0.11, 0.07], np.float32)
        kg = np.random.RandomState(1).randint(-qb, qb + 1, gh.shape[0])
        kh = np.random.RandomState(2).randint(0, qb + 1, gh.shape[0])
        mk = np.asarray(gh)[:, 2]
        # grid values pre-masked like the engine (grad*mask stays on grid)
        ghq = np.stack([kg * scales[0] * mk, kh * scales[1] * mk,
                        mk], 1).astype(np.float32)
        hq, cq = build_histogram_wave(
            binned, slot, jnp.asarray(ghq), max_bin=B, num_slots=slots,
            quant_bins=qb, quant_scales=jnp.asarray(scales))
        wq, wc = _host_hist(b_np, s_np, ghq, B, slots)
        # int32 accumulation then dequant: exact up to one float32 scale
        if not np.allclose(np.asarray(hq), wq, rtol=1e-6, atol=1e-5):
            failures.append("int8_exactness")
    except Exception as e:
        failures.append(f"int8_raised({type(e).__name__})")

    # 4. single-leaf row-major Pallas histogram vs segment lowering
    try:
        rows = jnp.asarray(np.ascontiguousarray(np.asarray(binned).T))
        mask = gh[:, 2]
        hp = build_histogram_rows_pallas(rows, gh[:, :2], mask, max_bin=B)
        hs = build_histogram(binned, gh[:, :2], mask, max_bin=B,
                             method="segment")
        if not np.allclose(np.asarray(hp), np.asarray(hs),
                           rtol=1e-5, atol=1e-4):
            failures.append("rows_pallas_vs_segment")
    except Exception as e:
        failures.append(f"rows_raised({type(e).__name__})")

    return "ok" if not failures else "fail:" + ",".join(failures)


if __name__ == "__main__":
    print(run_checks())
