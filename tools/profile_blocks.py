"""Block-shape tuning for the wave kernel: unpadded F, Fg=F single group,
row-tile sweep.  Shapes: 1M rows, 28 features, 256 bins, 128 gh lanes."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

N = 1 << 20
B = 256
REPS = 10

rng = np.random.RandomState(0)


def timeit(name, fn):
    @jax.jit
    def loop():
        def step(c, _):
            r = fn()
            return c + jnp.float32(jnp.sum(r[..., 0])), None
        out, _ = jax.lax.scan(step, jnp.float32(0), None, length=REPS)
        return out
    try:
        loop().block_until_ready()
    except Exception as e:
        print(f"{name:50s} FAILED: {str(e)[:150]}", flush=True)
        return
    t0 = time.time()
    loop().block_until_ready()
    dt = (time.time() - t0) / REPS
    print(f"{name:50s} {dt*1e3:8.2f} ms", flush=True)


def kern(Fg, lanes):
    def kernel(rows_ref, gh_ref, out_ref):
        @pl.when(pl.program_id(1) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)
        rows = rows_ref[...].astype(jnp.int32)
        ghv = gh_ref[...].astype(jnp.bfloat16)
        Rt = rows.shape[1]
        biota = jax.lax.broadcasted_iota(jnp.int32, (Fg, B, Rt), 1)
        oh = (rows[:, None, :] == biota).astype(jnp.bfloat16)
        acc = jax.lax.dot_general(
            oh.reshape(Fg * B, Rt), ghv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        out_ref[...] += acc.reshape(Fg, B, lanes)
    return kernel


def run(name, F, Fg, row_tile, lanes=128):
    # generate on DEVICE: host->device transfers ride a slow tunnel here
    key = jax.random.PRNGKey(0)
    binned = jax.jit(lambda: jax.random.randint(
        key, (F, N), 0, B, jnp.int32).astype(jnp.uint8))()
    gh = jax.jit(lambda: jax.random.normal(key, (N, lanes), jnp.float32))()

    def fn():
        return pl.pallas_call(
            kern(Fg, lanes),
            grid=(F // Fg, N // row_tile),
            in_specs=[pl.BlockSpec((Fg, row_tile), lambda g, i: (g, i)),
                      pl.BlockSpec((row_tile, lanes), lambda g, i: (i, 0))],
            out_specs=pl.BlockSpec((Fg, B, lanes), lambda g, i: (g, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((F, B, lanes), jnp.float32),
        )(binned, gh)
    timeit(name, fn)


run("F=32 Fg=8  Rt=512 (current)", 32, 8, 512)
run("F=28 Fg=28 Rt=512", 28, 28, 512)
run("F=28 Fg=28 Rt=256", 28, 28, 256)
run("F=28 Fg=28 Rt=1024", 28, 28, 1024)
run("F=28 Fg=14 Rt=512", 28, 14, 512)
run("F=28 Fg=7  Rt=512", 28, 7, 512)
run("F=28 Fg=4  Rt=512", 28, 4, 512)
run("F=32 Fg=32 Rt=512", 32, 32, 512)
run("F=28 Fg=28 Rt=512 lanes=256", 28, 28, 512, 256)
run("F=28 Fg=28 Rt=384", 28, 28, 384)
