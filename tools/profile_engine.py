"""Decompose the tree-growth iteration cost at bench shapes: time
grow_tree_wave alone for several num_leaves, on-device data."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from lightgbm_tpu.learner import FeatureMeta, GrowParams, grow_tree_wave
from lightgbm_tpu.ops.split import SplitParams

N = 1 << 20
F = 28
B = 255

key = jax.random.PRNGKey(0)
binned = jax.jit(lambda: jax.random.randint(
    key, (F, N), 0, B, jnp.int32).astype(jnp.uint8))()
grad = jax.jit(lambda: jax.random.normal(key, (N,), jnp.float32))()
hess = jax.jit(lambda: jax.random.uniform(
    key, (N,), jnp.float32, 0.05, 0.25))()
row_mask = jnp.ones(N, jnp.float32)
col_mask = jnp.ones(F, bool)
meta = FeatureMeta(
    num_bin=jnp.full(F, B, jnp.int32),
    missing_type=jnp.zeros(F, jnp.int32),
    default_bin=jnp.zeros(F, jnp.int32),
    penalty=jnp.ones(F, jnp.float32))


def timed(L, reps=5):
    params = GrowParams(num_leaves=L, max_bin=B, hist_method="pallas",
                        split=SplitParams(min_data_in_leaf=20))

    def run():
        t, lid = grow_tree_wave(binned, grad, hess, row_mask, col_mask,
                                meta, params)
        return t.leaf_value, lid

    lv, lid = run()
    lv.block_until_ready()
    t0 = time.time()
    for _ in range(reps):
        lv, lid = run()
    lv.block_until_ready()
    dt = (time.time() - t0) / reps
    print(f"L={L:4d}  {dt*1e3:8.1f} ms/tree", flush=True)


for L in (2, 8, 32, 64, 128, 255):
    timed(L)
