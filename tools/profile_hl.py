"""Probe: hi/lo outer-product decomposition of the wave histogram kernel.

The wave kernel's floor is the F*B*Rt bin one-hot built in VMEM every wave
(PERF_NOTES.md).  For waves with FEW computed slots S the one-hot factors:

  onehot_B(bin) = onehot_Bh(bin >> log2(Bl))  (x)  onehot_Bl(bin & (Bl-1))

  hist[f, bh, bl, (c,s)] = sum_n 1[hi=bh] * (1[lo=bl] * w[n, (c,s)])

LHS volume F*Bh*Rt, RHS volume F*Bl*C*S*Rt — for small S both are far
below F*B*Rt (e.g. S=1: 48 vs 256 lane-units per feature per row).

The RHS is built at FULL 128-lane efficiency with expander matmuls
(sub-128-lane elementwise ops pad to full vregs on TPU, so a naive per-f
[Rt, C*S] build would pay full-width cost):

  d  = [lo_rm | 1] @ [E ; -bl_pat]   (one matmul: lo value minus the
                                      column's bl target; 0 where matched)
  wt = w_sc @ T                      (CS -> F*Bl*CS column tiling)
  sc = where(d == 0, wt, 0)

Main dots pack P features into M (P*Bh <= 256) and P column blocks into N.

Usage: python tools/profile_hl.py   (on the TPU chip)
"""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

N = 1 << 20
F = 28
B = 256
C = 2
Rt = 512
REPS = 10

rng = np.random.RandomState(0)
binned_np = rng.randint(0, B, size=(F, N), dtype=np.uint8)


def timeit(name, fn, *args):
    # NOTE: through the axon tunnel block_until_ready can return early;
    # a host transfer (float()) is the only reliable completion barrier.
    # Inputs are perturbed per scan step so XLA cannot hoist the call.
    @jax.jit
    def loop(b, *rest):
        def step(c, x):
            r = fn(b, *rest[:-1], rest[-1].at[0, 0].add(x))
            return c + jnp.float32(jnp.sum(r[0][..., 0])), None
        out, _ = jax.lax.scan(step, jnp.float32(0),
                              jnp.arange(REPS, dtype=jnp.float32))
        return out
    try:
        float(loop(*args))
    except Exception as e:
        print(f"{name:44s} FAILED: {str(e)[:160]}", flush=True)
        return None
    best = 1e9
    for _ in range(3):
        t0 = time.time()
        float(loop(*args))
        best = min(best, (time.time() - t0) / REPS)
    print(f"{name:44s} {best*1e3:8.2f} ms", flush=True)
    return best


# ----------------------------------------------------------------------
# decomposed kernel
# ----------------------------------------------------------------------
def _hl_kernel(Fg, Bh, Bl, S, P):
    CS = C * S
    Wd = Fg * Bl * CS
    shift = Bl.bit_length() - 1

    def kernel(rows_ref, rows_rm_ref, slot_ref, gh_ref, out_ref, cnt_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)
            cnt_ref[...] = jnp.zeros_like(cnt_ref)
        i32, bf16 = jnp.int32, jnp.bfloat16
        rows = rows_ref[...].astype(i32)          # [Fg, Rt] (lanes=Rt)
        Rt = rows.shape[1]
        rows_rm = rows_rm_ref[...].astype(i32)    # [Rt, Fg] (sublanes=Rt)
        slot = slot_ref[...].astype(i32)          # [Rt, 1]
        gh = gh_ref[...]                          # [Rt, C+1]

        # LHS: hi one-hot [Fg, Bh, Rt]
        hi = rows >> shift
        biota = jax.lax.broadcasted_iota(i32, (Fg, Bh, Rt), 1)
        hi_oh = (hi[:, None, :] == biota).astype(bf16)

        # w_sc [Rt, CS]: slot one-hot x channels (c-major)
        soh = (slot == jax.lax.broadcasted_iota(i32, (Rt, S), 1))
        sohb = soh.astype(bf16)
        w_sc = jnp.concatenate(
            [sohb * gh[:, c:c + 1].astype(bf16) for c in range(C)], axis=1)

        # RHS via expander matmuls, all at full lane width:
        lo = (rows_rm & (Bl - 1)).astype(bf16)    # [Rt, Fg]
        ones = jnp.ones((Rt, 1), bf16)
        lhs2 = jnp.concatenate([lo, ones], axis=1)            # [Rt, Fg+1]
        colf = jax.lax.broadcasted_iota(i32, (Fg + 1, Wd), 1) // (Bl * CS)
        rowi = jax.lax.broadcasted_iota(i32, (Fg + 1, Wd), 0)
        blp = (jax.lax.broadcasted_iota(i32, (Fg + 1, Wd), 1) // CS) % Bl
        E2 = jnp.where(rowi == Fg, (-blp).astype(bf16),
                       (colf == rowi).astype(bf16))           # [Fg+1, Wd]
        d = jax.lax.dot_general(lhs2, E2, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        csp = jax.lax.broadcasted_iota(i32, (S if False else C * S, Wd), 1)
        Tm = (csp % CS ==
              jax.lax.broadcasted_iota(i32, (CS, Wd), 0)).astype(bf16)
        wt = jax.lax.dot_general(w_sc, Tm, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        sc = jnp.where(d == 0.0, wt, 0.0).astype(bf16)        # [Rt, Wd]

        # main dots: P features per dot
        BCS = Bl * CS
        for f0 in range(0, Fg, P):
            lhs = hi_oh[f0:f0 + P].reshape(P * Bh, Rt)
            rhs = sc[:, f0 * BCS:(f0 + P) * BCS]
            acc = jax.lax.dot_general(lhs, rhs, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            for p in range(P):
                out_ref[f0 + p] += acc[p * Bh:(p + 1) * Bh,
                                       p * BCS:(p + 1) * BCS]
        # ride-along exact counts
        mask8 = jnp.broadcast_to(gh[:, C:C + 1].astype(bf16), (Rt, 8)).T
        cacc = jax.lax.dot_general(mask8, sohb, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
        cnt_ref[...] += cacc
    return kernel


@functools.partial(jax.jit, static_argnames=("Bh", "Bl", "S", "P"))
def hist_hl(binned_fm, binned_rm, slot, gh, *, Bh, Bl, S, P):
    n = binned_fm.shape[1]
    slot = slot.reshape(n, 1)
    out, cnt = pl.pallas_call(
        _hl_kernel(F, Bh, Bl, S, P),
        grid=(n // Rt,),
        in_specs=[
            pl.BlockSpec((F, Rt), lambda i: (0, i)),
            pl.BlockSpec((Rt, F), lambda i: (i, 0)),
            pl.BlockSpec((Rt, 1), lambda i: (i, 0)),
            pl.BlockSpec((Rt, C + 1), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((F, Bh, Bl * C * S), lambda i: (0, 0, 0)),
            pl.BlockSpec((8, S), lambda i: (0, 0))],
        out_shape=[
            jax.ShapeDtypeStruct((F, Bh, Bl * C * S), jnp.float32),
            jax.ShapeDtypeStruct((8, S), jnp.float32)],
    )(binned_fm, binned_rm, slot, gh)
    # [F, Bh, (bl, c, s)] -> [S, F, B, C]
    h = out.reshape(F, Bh, Bl, C, S).transpose(4, 0, 1, 2, 3)
    return h.reshape(S, F, B, C), cnt[0]


def main():
    from lightgbm_tpu.ops.histogram import build_histogram_wave

    binned_fm = jnp.asarray(binned_np)
    binned_rm = jnp.asarray(binned_np.T)
    gvals = rng.randn(N, C).astype(np.float32)
    mask = np.ones((N, 1), np.float32)
    gh = jnp.asarray(np.concatenate([gvals, mask], axis=1))

    print(f"n={N}, F={F}, B={B}, C={C}, Rt={Rt}", flush=True)

    for S, Bh, Bl, P in [(1, 16, 16, 4), (2, 32, 8, 4), (4, 32, 8, 2),
                         (8, 64, 4, 2), (16, 64, 4, 1)]:
        slot_np = rng.randint(0, 2 * S, size=N).astype(np.int32)
        slot_np = np.where(slot_np < S, slot_np, 999999)  # sentinels
        slot = jnp.asarray(slot_np)
        # correctness vs XLA reference on a small prefix
        ns = 1 << 14
        h, cnt = jax.jit(functools.partial(hist_hl, Bh=Bh, Bl=Bl, S=S, P=P)
                         )(binned_fm[:, :ns][:, :Rt * (ns // Rt)],
                           binned_rm[:ns], slot[:ns], gh[:ns])
        oh_s = (np.asarray(slot[:ns])[:, None] == np.arange(S)[None, :])
        oh_b = (binned_np[:, :ns][:, :, None] ==
                np.arange(B)[None, None, :])
        ghb = np.asarray(jnp.asarray(gh[:ns, :C]).astype(jnp.bfloat16),
                         np.float64)  # kernel operands are bf16
        ref = np.einsum("ns,fnb,nc->sfbc", oh_s.astype(np.float64),
                        oh_b.astype(np.float64), ghb)
        err = np.abs(np.asarray(h, np.float64) - ref).max()
        refc = oh_s.sum(axis=0)
        errc = np.abs(np.asarray(cnt, np.float64)[:S] - refc).max()
        assert err < 1e-2 and errc == 0, (S, err, errc)
        timeit(f"hl S={S} Bh={Bh} Bl={Bl} P={P}",
               functools.partial(hist_hl, Bh=Bh, Bl=Bl, S=S, P=P),
               binned_fm, binned_rm, slot, gh)

    # current kernel baselines
    for Kb in (8, 16):
        slot = jnp.asarray(rng.randint(0, Kb, size=N).astype(np.int32))
        timeit(f"current wave kernel Kb={Kb}",
               functools.partial(build_histogram_wave, max_bin=B,
                                 num_slots=Kb), binned_fm, slot, gh)


if __name__ == "__main__":
    main()
