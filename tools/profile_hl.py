"""Probe: hi/lo outer-product decomposition of the wave histogram kernel.

The wave kernel's floor is the F*B*Rt bin one-hot built in VMEM every wave
(PERF_NOTES.md).  For waves with FEW computed slots S the one-hot factors:

  onehot_B(bin) = onehot_Bh(bin >> log2(Bl))  (x)  onehot_Bl(bin & (Bl-1))

  hist[f, bh, bl, (c,s)] = sum_n 1[hi=bh] * (1[lo=bl] * w[n, (c,s)])

LHS volume F*Bh*Rt, RHS volume F*Bl*C*S*Rt — for small S both are far
below F*B*Rt (e.g. S=1: 48 vs 256 lane-units per feature per row).

The RHS is built at FULL 128-lane efficiency with expander matmuls
(sub-128-lane elementwise ops pad to full vregs on TPU, so a naive per-f
[Rt, C*S] build would pay full-width cost):

  d  = [lo_rm | 1] @ [E ; -bl_pat]   (one matmul: lo value minus the
                                      column's bl target; 0 where matched)
  wt = w_sc @ T                      (CS -> F*Bl*CS column tiling)
  sc = where(d == 0, wt, 0)

Main dots pack P features into M (P*Bh <= 256) and P column blocks into N.

Usage: python tools/profile_hl.py   (on the TPU chip)
"""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

N = 1 << 20
F = 28
B = 256
C = 2
Rt = 512
REPS = 10

rng = np.random.RandomState(0)
binned_np = rng.randint(0, B, size=(F, N), dtype=np.uint8)


def timeit(name, fn, *args):
    # NOTE: through the axon tunnel block_until_ready can return early;
    # a host transfer (float()) is the only reliable completion barrier.
    # Inputs are perturbed per scan step so XLA cannot hoist the call.
    @jax.jit
    def loop(b, *rest):
        def step(c, x):
            r = fn(b, *rest[:-1], rest[-1].at[0, 0].add(x))
            return c + jnp.float32(jnp.sum(r[0][..., 0])), None
        out, _ = jax.lax.scan(step, jnp.float32(0),
                              jnp.arange(REPS, dtype=jnp.float32))
        return out
    try:
        float(loop(*args))
    except Exception as e:
        print(f"{name:44s} FAILED: {str(e)[:160]}", flush=True)
        return None
    best = 1e9
    for _ in range(3):
        t0 = time.time()
        float(loop(*args))
        best = min(best, (time.time() - t0) / REPS)
    print(f"{name:44s} {best*1e3:8.2f} ms", flush=True)
    return best


# the production kernel lives in ops/histogram.py; the probe wraps it so
# re-tuning always measures what ships
from lightgbm_tpu.ops.histogram import (build_histogram_wave,            # noqa: E402
                                        build_histogram_wave_hl)


def hist_hl(binned_fm, binned_rm, slot, gh, *, Bh, Bl, S, P):
    # Bh/Bl/P are chosen inside build_histogram_wave_hl (hl_split_of);
    # the probe's parameter columns document the expected pick
    return build_histogram_wave_hl(binned_fm, binned_rm, slot, gh,
                                   max_bin=B, num_slots=S, out_slots=S,
                                   row_tile=Rt)


def main():
    binned_fm = jnp.asarray(binned_np)
    binned_rm = jnp.asarray(binned_np.T)
    gvals = rng.randn(N, C).astype(np.float32)
    mask = np.ones((N, 1), np.float32)
    gh = jnp.asarray(np.concatenate([gvals, mask], axis=1))

    print(f"n={N}, F={F}, B={B}, C={C}, Rt={Rt}", flush=True)

    for S, Bh, Bl, P in [(1, 16, 16, 4), (2, 32, 8, 4), (4, 32, 8, 2),
                         (8, 64, 4, 2), (16, 64, 4, 1)]:
        slot_np = rng.randint(0, 2 * S, size=N).astype(np.int32)
        slot_np = np.where(slot_np < S, slot_np, 999999)  # sentinels
        slot = jnp.asarray(slot_np)
        # correctness vs XLA reference on a small prefix
        ns = 1 << 14
        h, cnt = functools.partial(hist_hl, Bh=Bh, Bl=Bl, S=S, P=P)(binned_fm[:, :ns][:, :Rt * (ns // Rt)],
                           binned_rm[:ns], slot[:ns], gh[:ns])
        oh_s = (np.asarray(slot[:ns])[:, None] == np.arange(S)[None, :])
        oh_b = (binned_np[:, :ns][:, :, None] ==
                np.arange(B)[None, None, :])
        ghb = np.asarray(jnp.asarray(gh[:ns, :C]).astype(jnp.bfloat16),
                         np.float64)  # kernel operands are bf16
        ref = np.einsum("ns,fnb,nc->sfbc", oh_s.astype(np.float64),
                        oh_b.astype(np.float64), ghb)
        err = np.abs(np.asarray(h, np.float64) - ref).max()
        refc = oh_s.sum(axis=0)
        errc = np.abs(np.asarray(cnt, np.float64)[:S] - refc).max()
        assert err < 1e-2 and errc == 0, (S, err, errc)
        timeit(f"hl S={S} Bh={Bh} Bl={Bl} P={P}",
               functools.partial(hist_hl, Bh=Bh, Bl=Bl, S=S, P=P),
               binned_fm, binned_rm, slot, gh)

    # current kernel baselines
    for Kb in (8, 16):
        slot = jnp.asarray(rng.randint(0, Kb, size=N).astype(np.int32))
        timeit(f"current wave kernel Kb={Kb}",
               functools.partial(build_histogram_wave, max_bin=B,
                                 num_slots=Kb), binned_fm, slot, gh)


if __name__ == "__main__":
    main()
