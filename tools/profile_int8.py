"""Can the one-hot build + dot go int8 end-to-end without an int32 detour?
Variants timed at bench shapes (1M rows, 32 padded features, 256 bins)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

N = 1 << 20
Fp = 32
B = 256
REPS = 10

rng = np.random.RandomState(0)
binned_fm = jnp.asarray(rng.randint(0, B, size=(Fp, N), dtype=np.uint8))
gh_bf = jnp.asarray(rng.randn(N, 128).astype(np.float32))
gh_i8 = jnp.asarray(rng.randint(-63, 64, size=(N, 128), dtype=np.int8))


def timeit(name, fn):
    @jax.jit
    def loop():
        def step(c, _):
            r = fn()
            return c + jnp.float32(jnp.sum(r[..., 0])), None
        out, _ = jax.lax.scan(step, jnp.float32(0), None, length=REPS)
        return out
    try:
        loop().block_until_ready()
    except Exception as e:
        print(f"{name:50s} FAILED: {str(e)[:120]}", flush=True)
        return
    t0 = time.time()
    loop().block_until_ready()
    dt = (time.time() - t0) / REPS
    print(f"{name:50s} {dt*1e3:8.2f} ms", flush=True)


def build_kernel(oh_dtype, gh_dtype, acc_dtype, via_i32=False, do_dot=True,
                 dims3=False):
    def kernel(rows_ref, gh_ref, out_ref):
        @pl.when(pl.program_id(1) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)
        Fg = rows_ref.shape[0]
        rows = rows_ref[...].astype(jnp.int32)
        ghv = gh_ref[...].astype(gh_dtype)
        Rt = rows.shape[1]
        biota = jax.lax.broadcasted_iota(jnp.int32, (Fg, B, Rt), 1)
        eq = rows[:, None, :] == biota
        if via_i32:
            oh = jnp.where(eq, 1, 0).astype(oh_dtype)
        else:
            oh = eq.astype(oh_dtype)
        if do_dot:
            if dims3:
                acc = jax.lax.dot_general(
                    oh, ghv, (((2,), (0,)), ((), ())),
                    preferred_element_type=acc_dtype)
                out_ref[...] += acc
            else:
                acc = jax.lax.dot_general(
                    oh.reshape(Fg * B, Rt), ghv, (((1,), (0,)), ((), ())),
                    preferred_element_type=acc_dtype)
                out_ref[...] += acc.reshape(Fg, B, ghv.shape[-1])
        else:
            out_ref[...] += jnp.sum(oh, axis=2).astype(acc_dtype)[:, :, None]
    return kernel


def run(name, oh_dtype, gh, gh_dtype, acc_dtype, lanes=128, via_i32=False,
        do_dot=True, row_tile=512, dims3=False, Fg=8):
    ghl = gh[:, :lanes]

    def fn():
        out_lanes = lanes if do_dot else 1
        return pl.pallas_call(
            build_kernel(oh_dtype, gh_dtype, acc_dtype, via_i32, do_dot,
                         dims3),
            grid=(Fp // Fg, N // row_tile),
            in_specs=[pl.BlockSpec((Fg, row_tile), lambda g, i: (g, i)),
                      pl.BlockSpec((row_tile, lanes), lambda g, i: (i, 0))],
            out_specs=pl.BlockSpec((Fg, B, out_lanes), lambda g, i: (g, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((Fp, B, out_lanes), acc_dtype),
        )(binned_fm, ghl).astype(jnp.float32)
    timeit(name, fn)


run("build i8 direct, no dot", jnp.int8, gh_i8, jnp.int8, jnp.int32,
    do_dot=False)
run("build i8 via i32 where, no dot", jnp.int8, gh_i8, jnp.int8, jnp.int32,
    via_i32=True, do_dot=False)
run("build bf16 direct, no dot", jnp.bfloat16, gh_bf, jnp.bfloat16,
    jnp.float32, do_dot=False)
run("i8 oh x i8 gh -> i32, 128 lanes", jnp.int8, gh_i8, jnp.int8, jnp.int32)
run("i8 oh x i8 gh -> i32, 64 lanes", jnp.int8, gh_i8, jnp.int8, jnp.int32,
    lanes=64)
run("i8 oh x bf16 gh -> f32, 128 lanes", jnp.int8, gh_bf, jnp.bfloat16,
    jnp.float32)
run("bf16 oh x bf16 gh -> f32, 128 lanes (ref)", jnp.bfloat16, gh_bf,
    jnp.bfloat16, jnp.float32)
run("bf16 3-D dot (no reshape), 128 lanes", jnp.bfloat16, gh_bf,
    jnp.bfloat16, jnp.float32, dims3=True)
run("bf16 Rt=256", jnp.bfloat16, gh_bf, jnp.bfloat16, jnp.float32,
    row_tile=256)
run("bf16 Rt=1024", jnp.bfloat16, gh_bf, jnp.bfloat16, jnp.float32,
    row_tile=1024)
run("i8 Rt=1024 i8 gh", jnp.int8, gh_i8, jnp.int8, jnp.int32, row_tile=1024)
run("i8 Rt=2048 i8 gh", jnp.int8, gh_i8, jnp.int8, jnp.int32, row_tile=2048)
