"""Split the per-wave cost into (one-hot build) vs (MXU dot) and measure
the primitives a subtraction/compaction redesign needs (gather, scatter,
cumsum) at bench shapes.  End-to-end scan-timed on the real chip."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

N = 1 << 20
F = 28
Fp = 32
B = 256
REPS = 10

rng = np.random.RandomState(0)
binned_fm = jnp.asarray(rng.randint(0, B, size=(Fp, N), dtype=np.uint8))
binned_rm = jnp.asarray(rng.randint(0, B, size=(N, Fp), dtype=np.uint8))
gh3 = jnp.asarray(rng.randn(N, 3).astype(np.float32))
perm = jnp.asarray(rng.permutation(N).astype(np.int32))
half_idx = jnp.asarray(np.sort(rng.permutation(N)[: N // 2]).astype(np.int32))
mask = jnp.asarray((rng.rand(N) < 0.5).astype(np.float32))


def timeit(name, fn):
    @jax.jit
    def loop():
        def step(c, _):
            r = fn()
            return c + jnp.float32(jnp.sum(r[0][..., 0]) if isinstance(r, tuple)
                                   else jnp.sum(r[..., 0])), None
        out, _ = jax.lax.scan(step, jnp.float32(0), None, length=REPS)
        return out

    loop().block_until_ready()
    t0 = time.time()
    loop().block_until_ready()
    dt = (time.time() - t0) / REPS
    print(f"{name:45s} {dt*1e3:8.2f} ms", flush=True)


# --- A: one-hot build only (reduce, no dot) -------------------------------
def _oh_only_kernel(Fg, Bg):
    def kernel(rows_ref, out_ref):
        @pl.when(pl.program_id(1) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)
        rows = rows_ref[...].astype(jnp.int32)
        Rt = rows.shape[1]
        biota = jax.lax.broadcasted_iota(jnp.int32, (Fg, Bg, Rt), 1)
        oh = (rows[:, None, :] == biota).astype(jnp.float32)
        out_ref[...] += jnp.sum(oh, axis=2)
    return kernel


def oh_only(row_tile=512, Fg=8):
    out = pl.pallas_call(
        _oh_only_kernel(Fg, B),
        grid=(Fp // Fg, N // row_tile),
        in_specs=[pl.BlockSpec((Fg, row_tile), lambda g, i: (g, i))],
        out_specs=pl.BlockSpec((Fg, B), lambda g, i: (g, 0)),
        out_shape=jax.ShapeDtypeStruct((Fp, B), jnp.float32),
    )(binned_fm)
    return out


# --- B: one-hot + 1-lane-tile dot ----------------------------------------
def _oh_dot_kernel(Fg, Bg, NLanes):
    def kernel(rows_ref, gh_ref, out_ref):
        @pl.when(pl.program_id(1) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)
        rows = rows_ref[...].astype(jnp.int32)
        ghv = gh_ref[...].astype(jnp.bfloat16)  # [Rt, NLanes]
        Rt = rows.shape[1]
        biota = jax.lax.broadcasted_iota(jnp.int32, (Fg, Bg, Rt), 1)
        oh = (rows[:, None, :] == biota).astype(jnp.bfloat16)
        acc = jax.lax.dot_general(
            oh.reshape(Fg * Bg, Rt), ghv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        out_ref[...] += acc.reshape(Fg, Bg, NLanes)
    return kernel


def oh_dot(NLanes=128, row_tile=512, Fg=8):
    ghn = jnp.broadcast_to(gh3[:, :1], (N, NLanes))
    out = pl.pallas_call(
        _oh_dot_kernel(Fg, B, NLanes),
        grid=(Fp // Fg, N // row_tile),
        in_specs=[pl.BlockSpec((Fg, row_tile), lambda g, i: (g, i)),
                  pl.BlockSpec((row_tile, NLanes), lambda g, i: (i, 0))],
        out_specs=pl.BlockSpec((Fg, B, NLanes), lambda g, i: (g, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((Fp, B, NLanes), jnp.float32),
    )(binned_fm, ghn)
    return out


timeit("A one-hot only (Fg=8, Rt=512)", oh_only)
timeit("A one-hot only (Fg=8, Rt=1024)", functools.partial(oh_only, 1024))
timeit("A one-hot only (Fg=16, Rt=512)", functools.partial(oh_only, 512, 16))
timeit("B one-hot + dot 128 lanes", oh_dot)
timeit("B one-hot + dot 256 lanes", functools.partial(oh_dot, 256))
timeit("B one-hot + dot 128 lanes Rt=1024",
       functools.partial(oh_dot, 128, 1024))

# --- primitives -----------------------------------------------------------
timeit("gather rows rm [N/2, 32]u8",
       lambda: jnp.take(binned_rm, half_idx, axis=0).astype(jnp.float32))
timeit("gather cols fm [32, N/2]u8",
       lambda: jnp.take(binned_fm, half_idx, axis=1).astype(jnp.float32)[:1].T)
timeit("gather gh rows [N/2, 3]f32",
       lambda: jnp.take(gh3, half_idx, axis=0))
timeit("cumsum mask [N]f32", lambda: jnp.cumsum(mask)[:, None])
timeit("scatter-compact idx (N/2 unique)",
       lambda: jnp.zeros(N // 2, jnp.int32).at[
           jnp.clip(jnp.cumsum(mask).astype(jnp.int32) - 1, 0, N // 2 - 1)
       ].set(jnp.arange(N, dtype=jnp.int32), mode="drop",
             unique_indices=False)[:, None].astype(jnp.float32))
timeit("full permute rows rm [N, 32]u8",
       lambda: jnp.take(binned_rm, perm, axis=0).astype(jnp.float32))
