"""Per-wave histogram kernel cost curve on the real chip.

Times build_histogram_wave at bench shapes (1M rows, 28 features, 256 bins)
across slot counts, many reps inside one jit (scan) so tunnel dispatch noise
doesn't pollute the numbers.  Purpose: decide whether the wave cost is
VPU-bound (flat in NL) or MXU-bound (linear in NL beyond ~64 slots).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu.ops.histogram import build_histogram_wave

N = 1 << 20
F = 28
B = 256
REPS = 10

rng = np.random.RandomState(0)
binned = jnp.asarray(rng.randint(0, B, size=(F, N), dtype=np.uint8))
gh = jnp.asarray(rng.randn(N, 3).astype(np.float32))


def timed(num_slots):
    slot = jnp.asarray(rng.randint(0, num_slots, size=N, dtype=np.int32))

    def one(c, _):
        h, cnt = build_histogram_wave(binned, slot, gh, max_bin=B,
                                      num_slots=num_slots)
        return c + h[0, 0, 0, 0] + cnt[0], None

    @jax.jit
    def loop():
        out, _ = jax.lax.scan(one, jnp.float32(0), None, length=REPS)
        return out

    loop().block_until_ready()  # compile
    t0 = time.time()
    r = loop().block_until_ready()
    dt = (time.time() - t0) / REPS
    return dt, float(r)


for nl in (8, 16, 32, 64, 128, 256):
    dt, _ = timed(nl)
    print(f"NL={nl:4d}  {dt*1e3:8.2f} ms/call", flush=True)
