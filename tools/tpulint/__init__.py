"""tpulint — JAX/TPU-aware static analysis for the lightgbm_tpu package.

Rules (docs/StaticAnalysis.md):

* no-host-sync-in-jit      — float()/int()/bool()/.item()/np.asarray()/
                             .block_until_ready() on traced values in
                             the interprocedural call graph rooted at
                             the jax.jit entry points (v2: methods,
                             dispatch tables, higher-order arguments)
* no-tracer-branch         — Python if/while/assert on traced values
* no-dynamic-shape-in-jit  — nonzero/unique/1-arg where without size=,
                             boolean-mask indexing, traced shape args
* donated-buffer-reuse     — reading a binding after passing it in a
                             donated position of a jitted entry
* spmd-axis-discipline     — collective/PartitionSpec axis names match
                             the declared mesh axes; collectives live
                             under shard_map
* donated-sharding         — jit(shard_map(...), donate_argnums=...)
                             must pass explicit in_shardings
* explicit-dtype           — jnp.zeros/ones/full/arange/array in device
                             code must pass a dtype
* collective-discipline    — lax.psum/pmean/all_gather only in
                             parallel/ or distributed.py
* donate-argnums           — score/grad/hess-shaped jit entries donate
* no-device-put-in-loop    — no H2D transfers in Python loop bodies
* no-bare-print            — all output through utils.log / event log
* config-doc-sync          — config.py PARAMS <-> docs/Parameters.md
* signal-handler-safety    — no unbounded blocking (queue put/join,
                             lock acquire, event wait) or jax dispatch
                             reachable from signal handlers / watchdog
                             exit paths (v3 concurrency roots)
* thread-shared-state      — lockset race detection: attributes and
                             globals written on one concurrent root
                             (thread/handler/main) and accessed on
                             another with no common lock
* rng-stream-discipline    — draw-once PRNG keys, no np.random module
                             state, iteration-keyed seeds (the
                             byte-exact-resume RNG contract)
* atomic-write-discipline  — write-mode open() under reliability/ must
                             use the temp+os.replace atomic writer

Run:  python -m tools.tpulint [package_dir]
      [--format=json|text|github|sarif] [--jobs=N]
      [--baseline=FILE] [--write-baseline=FILE] [--list-suppressions]
Suppress:  # tpulint: disable=<rule>[,<rule>] -- <justification>
"""

from .core import (Finding, LintContext, Report, Rule, RULES,  # noqa: F401
                   apply_baseline, audit_suppressions, baseline_counts,
                   iter_suppressions, register, run_lint, to_sarif,
                   write_baseline)

__all__ = ["Finding", "LintContext", "Report", "Rule", "RULES",
           "apply_baseline", "audit_suppressions", "baseline_counts",
           "iter_suppressions", "register", "run_lint", "to_sarif",
           "write_baseline"]
