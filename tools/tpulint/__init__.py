"""tpulint — JAX/TPU-aware static analysis for the lightgbm_tpu package.

Rules (docs/StaticAnalysis.md):

* no-host-sync-in-jit    — float()/int()/bool()/.item()/np.asarray()/
                           .block_until_ready() on traced values in the
                           static call graph rooted at the jax.jit entry
                           points
* no-tracer-branch       — Python if/while/assert on traced values
* explicit-dtype         — jnp.zeros/ones/full/arange/array in device
                           code must pass a dtype
* collective-discipline  — lax.psum/pmean/all_gather only in parallel/
                           or distributed.py
* no-bare-print          — all output through utils.log / the event log
* config-doc-sync        — config.py PARAMS <-> docs/Parameters.md

Run:  python -m tools.tpulint [package_dir] [--format=json|text]
Suppress:  # tpulint: disable=<rule>[,<rule>] -- <justification>
"""

from .core import (Finding, LintContext, Report, Rule, RULES,  # noqa: F401
                   register, run_lint)

__all__ = ["Finding", "LintContext", "Report", "Rule", "RULES",
           "register", "run_lint"]
