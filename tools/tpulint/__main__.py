"""Console entry: `python -m tools.tpulint [package_dir] [options]`.

Exit status is the CI contract (wired into tier-1 via
tests/test_tpulint.py; external CI calls this exactly the same way):

    0  no unsuppressed findings
    1  unsuppressed findings (or a rule/usage error)

Options:
    --format=text|json   report format (default text; json is the
                         machine-readable report)
    --rules=a,b          run only the named rules
    --list-rules         print the registry and exit
"""

from __future__ import annotations

import argparse
import sys

from .core import RULES, run_lint


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.tpulint",
        description="JAX/TPU-aware static analysis (docs/StaticAnalysis.md)")
    ap.add_argument("package_dir", nargs="?", default="lightgbm_tpu",
                    help="package tree to lint (default: lightgbm_tpu)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        from . import rules as _rules  # noqa: F401  (registers)
        for name in sorted(RULES):
            sys.stdout.write(f"{name}: {RULES[name].description}\n")
        return 0

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    try:
        report = run_lint(args.package_dir, rules=rules)
    except KeyError as e:
        sys.stderr.write(f"tpulint: {e.args[0]}\n")
        return 1
    if args.format == "json":
        sys.stdout.write(report.to_json() + "\n")
    else:
        sys.stdout.write(report.render_text() + "\n")
    return 1 if report.active else 0


if __name__ == "__main__":
    sys.exit(main())
