"""Console entry: `python -m tools.tpulint [package_dir] [options]`.

Exit status is the CI contract (wired into tier-1 via
tests/test_tpulint.py; external CI calls this exactly the same way):

    0  no unsuppressed findings (with --baseline: no NEW findings)
    1  unsuppressed/new findings (or a rule/usage error)

Options:
    --ir                       additionally run the jaxpr-level IR
                               audit over the package's
                               _lint_entries.py manifest (abstract
                               trace of every hot jitted entry;
                               docs/StaticAnalysis.md v4)
    --format=text|json|github|sarif
                               report format (github emits workflow
                               annotations ::error file=...,line=...;
                               sarif emits SARIF 2.1.0 for standard PR
                               annotation tooling)
    --rules=a,b                run only the named rules
    --list-rules               print the registry and exit
    --baseline=FILE            accept the legacy findings recorded in
                               FILE; fail only on NEW ones
    --write-baseline=FILE      record the current findings as the
                               baseline and exit 0
    --list-suppressions        audit every `# tpulint: disable` in the
                               package (path, line, rules, why); runs
                               the suite and exits 1 on STALE
                               suppressions that mask nothing
    --jobs=N                   process-pool width for the per-file rule
                               passes (default: one per CPU; 1 = serial)
    --no-cache                 disable the mtime-keyed analysis cache
                               (.tpulint_cache.json next to the package)
"""

from __future__ import annotations

import argparse
import json
import sys

from .core import (RULES, apply_baseline, audit_suppressions,
                   default_cache_path, run_lint, to_sarif, write_baseline)


def _github_line(f) -> str:
    return (f"::error file={f.path},line={f.line},col={f.col},"
            f"title=tpulint {f.rule}::{f.message}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.tpulint",
        description="JAX/TPU-aware static analysis (docs/StaticAnalysis.md)")
    ap.add_argument("package_dir", nargs="?", default="lightgbm_tpu",
                    help="package tree to lint (default: lightgbm_tpu)")
    ap.add_argument("--ir", action="store_true",
                    help="additionally run the jaxpr-level IR audit "
                         "(abstract trace of the _lint_entries.py "
                         "manifest entries)")
    ap.add_argument("--format", choices=("text", "json", "github",
                                         "sarif"),
                    default="text")
    ap.add_argument("--jobs", type=int, default=None, metavar="N",
                    help="process-pool width for per-file rules "
                         "(default: one per CPU; 1 = serial)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="accept legacy findings from FILE; fail only "
                         "on new ones")
    ap.add_argument("--write-baseline", default=None, metavar="FILE",
                    help="record current findings as the baseline")
    ap.add_argument("--list-suppressions", action="store_true",
                    help="list every justified tpulint disable comment")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the mtime-keyed analysis cache")
    args = ap.parse_args(argv)

    if args.list_rules:
        from . import rules as _rules  # noqa: F401  (registers)
        for name in sorted(RULES):
            sys.stdout.write(f"{name}: {RULES[name].description}\n")
        return 0

    if args.list_suppressions:
        n = stale = 0
        cache = (None if args.no_cache
                 else default_cache_path(args.package_dir))
        for path, line, rules, why, used in sorted(audit_suppressions(
                args.package_dir, cache_path=cache, ir=args.ir)):
            n += 1
            mark = ""
            if not used:
                stale += 1
                mark = " (STALE: masks no finding — remove it)"
            sys.stdout.write(f"{path}:{line}: [{','.join(rules)}] "
                             f"{why or '(MISSING JUSTIFICATION)'}{mark}\n")
        sys.stdout.write(f"{n} suppression(s), {stale} stale\n")
        return 1 if stale else 0

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    cache = None if args.no_cache else default_cache_path(args.package_dir)
    try:
        report = run_lint(args.package_dir, rules=rules, cache_path=cache,
                          jobs=args.jobs, ir=args.ir)
    except KeyError as e:
        sys.stderr.write(f"tpulint: {e.args[0]}\n")
        return 1

    if args.write_baseline:
        write_baseline(args.write_baseline, report)
        sys.stdout.write(f"baseline written: {args.write_baseline} "
                         f"({len(report.active)} finding(s) accepted)\n")
        return 0

    failing = report.active
    accepted = 0
    if args.baseline:
        try:
            failing, accepted = apply_baseline(report, args.baseline)
        except (OSError, ValueError) as e:
            sys.stderr.write(f"tpulint: cannot read baseline "
                             f"{args.baseline}: {e}\n")
            return 1

    if args.format == "json":
        sys.stdout.write(report.to_json() + "\n")
    elif args.format == "sarif":
        sys.stdout.write(json.dumps(
            to_sarif(report, failing if args.baseline else None),
            indent=2) + "\n")
    elif args.format == "github":
        for f in failing:
            sys.stdout.write(_github_line(f) + "\n")
        sys.stdout.write(f"{len(failing)} new finding(s), "
                         f"{accepted} accepted by baseline, "
                         f"{len(report.suppressed)} suppressed\n")
    else:
        sys.stdout.write(report.render_text() + "\n")
        if args.baseline:
            sys.stdout.write(f"{len(failing)} new finding(s), "
                             f"{accepted} accepted by baseline\n")
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
