"""Static jit call graph + parameter-taint analysis for tpulint (v2).

The device-code rules (no-host-sync-in-jit, no-tracer-branch,
no-dynamic-shape-in-jit) need to know which code runs under `jax.jit`
tracing and which values are tracers there.  Both are approximated
statically:

* **Roots**: every function wrapped in jit anywhere in the package —
  `@jax.jit`, `@functools.partial(jax.jit, static_argnames=...)`, the
  assignment form `f = jax.jit(g, ...)`, the attribute form
  `self._fn = jax.jit(g, ...)`, and `jax.jit(factory(...))` where the
  in-package factory returns a locally-defined function.
  `static_argnames`/`static_argnums` are honored: those parameters are
  Python values at trace time, and branching on them is exactly how
  static configuration is supposed to work.

* **Call graph (v2 — interprocedural)**: from each root, callees are
  resolved through

  - direct calls to package functions (same module or imported, with
    re-export chains like `learner/__init__.py` followed);
  - **method calls**: `self.m()` / `cls.m()` resolve through a class-
    hierarchy pass (in-package base classes included), binding call-
    site taints to the method's parameters after `self`;
  - **containers**: names bound to dict/list/tuple literals of
    functions (`TABLE = {"a": f}`; `self._fns[k] = fn`) — a call
    through the container (`TABLE[key](...)`) reaches every member;
  - **value bindings**: names bound to functions indirectly
    (`g = f`, `g = jax.jit(f)`, `g = a if c else b`, factory returns);
  - **function-valued arguments**: a function reference passed as an
    argument marks the callee's parameter, and calls of that parameter
    inside the callee dispatch to the referenced functions.

  Taint is iterated to a fixpoint, so it flows through helper layers
  (grow_tree -> find_best_split -> leaf_gain), through method
  indirection, and through the jit-entry tables the boosting loop
  dispatches on.

* **Taint**: within one root, a flat name->tainted environment seeded by
  the non-static parameters.  Assignments propagate taint through
  expressions; `.shape/.ndim/.dtype/.size` access yields a STATIC
  value even on a tracer (that's how jit code legitimately branches on
  geometry), and `is`/`is not` comparisons are host-safe identity
  checks.  Functions passed to `lax.fori_loop`/`while_loop`/`scan`/
  `cond`/`switch` and `jax.vmap` get their parameters tainted per the
  lax calling contract (the loop index and carry are tracers).

Not resolved (kept deliberately out to hold false positives near
zero): methods on objects whose class cannot be determined from the
expression (`objective.get_gradients(...)` on a closure variable), and
constructor calls.  The fixture tests in tests/test_tpulint.py pin the
contract.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# attributes that are static (Python) values even on a tracer
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type", "sharding"}
# builtins whose call result is always a static Python value
STATIC_CALLS = {"len", "isinstance", "range", "type", "getattr", "hasattr",
                "max", "min"}

_LAX_HOF = {
    # func attr -> list of (callee_arg_index, callee_param_slice)
    # fori_loop(lo, hi, body, init): body(i, carry) — both traced
    "fori_loop": [(2, 2)],
    # while_loop(cond, body, init): each takes the traced carry
    "while_loop": [(0, 1), (1, 1)],
    # scan(f, init, xs): f(carry, x) — both traced
    "scan": [(0, 2)],
    # cond(pred, true_fn, false_fn, *operands): operands traced
    "cond": [(1, 99), (2, 99)],
    # switch(index, branches, *operands): can't see into branch lists
    # unless they are literal [name, ...] — handled separately
    "switch": [],
}

_JIT_NAMES = ("jax.jit", "jit")
_PARTIAL_NAMES = ("functools.partial", "partial")


def cached_walk(root: ast.AST) -> List[ast.AST]:
    """`list(ast.walk(root))`, memoized on the node.  The rules walk the
    same file and function subtrees many times over; caching the flat
    node list once per root cut the cold full-package lint measurably
    (ast.walk's deque/iter_child_nodes machinery dominated the
    profile)."""
    lst = getattr(root, "_tpulint_walk", None)
    if lst is None:
        lst = list(ast.walk(root))
        try:
            root._tpulint_walk = lst  # type: ignore[attr-defined]
        except AttributeError:
            pass
    return lst


@dataclass
class FuncInfo:
    """One function definition (top-level, method, or nested)."""
    node: ast.AST                  # FunctionDef / Lambda
    module: "ModuleInfo"
    qualname: str
    jit_root: bool = False
    owner_class: Optional["ClassInfo"] = None
    static_params: Set[str] = field(default_factory=set)
    # accumulated tainted parameter names (grows monotonically)
    tainted_params: Set[str] = field(default_factory=set)
    # param name -> functions possibly bound to it (higher-order flow)
    param_funcs: Dict[str, Set[int]] = field(default_factory=dict)

    @property
    def param_names(self) -> List[str]:
        a = self.node.args
        names = [p.arg for p in getattr(a, "posonlyargs", [])]
        names += [p.arg for p in a.args]
        names += [p.arg for p in a.kwonlyargs]
        return names


@dataclass
class ClassInfo:
    """One in-package class: methods + function-valued attributes."""
    name: str
    module: "ModuleInfo"
    node: ast.ClassDef
    methods: Dict[str, FuncInfo] = field(default_factory=dict)
    bases: List["ClassInfo"] = field(default_factory=list)
    # attr name -> functions possibly bound via `self.attr = ...` /
    # `self.attr[k] = ...` / class-body assignment (grows monotonically)
    attr_funcs: Dict[str, Set[int]] = field(default_factory=dict)
    # attr name -> dotted constructor it was assigned from
    # (`self._q = queue.Queue(...)` -> "queue.Queue"): the concurrency
    # rules use this to recognize lock/queue/event-typed attributes
    attr_types: Dict[str, str] = field(default_factory=dict)

    def find_method(self, name: str) -> Optional[FuncInfo]:
        if name in self.methods:
            return self.methods[name]
        for base in self.bases:
            m = base.find_method(name)
            if m is not None:
                return m
        return None

    def find_attr_funcs(self, name: str) -> Set[int]:
        out: Set[int] = set(self.attr_funcs.get(name, ()))
        for base in self.bases:
            out |= base.find_attr_funcs(name)
        return out

    def find_attr_type(self, name: str) -> Optional[str]:
        if name in self.attr_types:
            return self.attr_types[name]
        for base in self.bases:
            t = base.find_attr_type(name)
            if t is not None:
                return t
        return None


class ModuleInfo:
    """Per-file index: imports, top-level functions, classes, and
    module-level value bindings."""

    def __init__(self, pf, package_name: str):
        self.pf = pf
        self.package_name = package_name
        # module dotted name, e.g. lightgbm_tpu.learner.grow
        parts = pf.rel[:-3].split(os.sep)
        self.is_package = parts[-1] == "__init__"
        if self.is_package:
            parts = parts[:-1]
        self.dotted = ".".join(parts)
        self.imports: Dict[str, Tuple[str, Optional[str]]] = {}
        self.top_funcs: Dict[str, FuncInfo] = {}
        self.top_classes: Dict[str, ClassInfo] = {}
        # module-level name -> RHS expression(s) it was assigned
        self.binding_exprs: Dict[str, List[ast.AST]] = {}
        # resolved: module-level name -> referenced functions
        self.value_bindings: Dict[str, Set[int]] = {}
        if pf.tree is not None:
            self._index(pf.tree)

    def _resolve_relative(self, level: int, module: Optional[str]) -> str:
        base = self.dotted.split(".")
        # level=1 strips the module's own name, 2 strips one package, ...
        # — except in a package __init__, whose dotted name IS the
        # package, so level 1 strips nothing there
        strip = level - 1 if self.is_package else level
        if strip:
            base = base[:len(base) - strip]
        if module:
            base = base + module.split(".")
        return ".".join(base)

    def _index(self, tree: ast.AST) -> None:
        for node in cached_walk(tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    self.imports[al.asname or al.name.split(".")[0]] = (
                        al.name, None)
            elif isinstance(node, ast.ImportFrom):
                mod = (self._resolve_relative(node.level, node.module)
                       if node.level else (node.module or ""))
                for al in node.names:
                    self.imports[al.asname or al.name] = (mod, al.name)
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.top_funcs[node.name] = FuncInfo(
                    node=node, module=self, qualname=node.name)
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(name=node.name, module=self, node=node)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        ci.methods[item.name] = FuncInfo(
                            node=item, module=self,
                            qualname=f"{node.name}.{item.name}",
                            owner_class=ci)
                self.top_classes[node.name] = ci
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.binding_exprs.setdefault(t.id, []).append(
                            node.value)

    def dotted_of(self, expr: ast.AST) -> Optional[str]:
        """Resolve an expression to a dotted module path, following this
        module's imports: `np.asarray` -> numpy.asarray, `jax.lax.psum`
        -> jax.lax.psum, `jit` imported from jax -> jax.jit."""
        parts: List[str] = []
        while isinstance(expr, ast.Attribute):
            parts.append(expr.attr)
            expr = expr.value
        if not isinstance(expr, ast.Name):
            return None
        base = expr.id
        if base in self.imports:
            mod, attr = self.imports[base]
            head = mod + ("." + attr if attr else "")
        else:
            head = base
        return ".".join([head] + list(reversed(parts)))


def module_info_for(ctx, pf) -> ModuleInfo:
    """One shared ModuleInfo per parsed file (cached on the PyFile): the
    per-file rules and the package index all read the same parse instead
    of re-indexing imports/classes once per rule."""
    mi = getattr(pf, "_tpulint_mi", None)
    if mi is None:
        mi = ModuleInfo(pf, ctx.package_name)
        pf._tpulint_mi = mi  # type: ignore[attr-defined]
    return mi


class PackageIndex:
    """All modules of the linted package + jit roots + class hierarchy +
    value bindings."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.modules: Dict[str, ModuleInfo] = {}
        # id(FuncInfo) -> FuncInfo (value bindings store ids so the sets
        # stay hashable across dataclass instances)
        self.funcs_by_id: Dict[int, FuncInfo] = {}
        for pf in ctx.files:
            mi = module_info_for(ctx, pf)
            self.modules[mi.dotted] = mi
        self._register_known_funcs()
        self._link_bases()
        self._resolve_bindings()
        for mi in self.modules.values():
            self._mark_jit_roots(mi)
        self._collect_class_attrs()

    def func(self, fid: int) -> FuncInfo:
        return self.funcs_by_id[fid]

    def _remember(self, fi: FuncInfo) -> int:
        self.funcs_by_id[id(fi)] = fi
        return id(fi)

    def _register_known_funcs(self) -> None:
        for mi in self.modules.values():
            for fi in mi.top_funcs.values():
                self._remember(fi)
            for ci in mi.top_classes.values():
                for fi in ci.methods.values():
                    self._remember(fi)

    def _link_bases(self) -> None:
        for mi in self.modules.values():
            for ci in mi.top_classes.values():
                for base in ci.node.bases:
                    bci = self._resolve_class(mi, base)
                    if bci is not None:
                        ci.bases.append(bci)

    def _resolve_class(self, mi: ModuleInfo, expr: ast.AST
                       ) -> Optional[ClassInfo]:
        if isinstance(expr, ast.Name):
            if expr.id in mi.top_classes:
                return mi.top_classes[expr.id]
            imp = mi.imports.get(expr.id)
            if imp:
                tgt = self.modules.get(imp[0])
                if tgt and imp[1] and imp[1] in tgt.top_classes:
                    return tgt.top_classes[imp[1]]
        elif isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                            ast.Name):
            imp = mi.imports.get(expr.value.id)
            if imp and imp[1] is None:
                tgt = self.modules.get(imp[0])
                if tgt and expr.attr in tgt.top_classes:
                    return tgt.top_classes[expr.attr]
        return None

    # ---- jit root discovery ----

    def _mark_jit_roots(self, mi: ModuleInfo) -> None:
        if mi.pf.tree is None:
            return
        # decorated defs (any nesting depth)
        for node in cached_walk(mi.pf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    statics = self._jit_decorator_statics(mi, dec, node)
                    if statics is not None:
                        fi = self._func_for_def(mi, node)
                        fi.jit_root = True
                        fi.static_params |= statics
            elif isinstance(node, ast.Call):
                # assignment/expression form: jax.jit(fn, ...)
                if self._is_jit_name(mi, node.func) and node.args:
                    for fi in self._jit_target_funcs(mi, node.args[0]):
                        fi.jit_root = True
                        fi.static_params |= self._static_names_of(
                            mi, node, fi.node)

    def _func_for_def(self, mi: ModuleInfo, node: ast.AST) -> FuncInfo:
        """FuncInfo for a def node, registering nested/method defs that
        are not already indexed."""
        fi = mi.top_funcs.get(getattr(node, "name", ""))
        if fi is not None and fi.node is node:
            return fi
        for ci in mi.top_classes.values():
            m = ci.methods.get(getattr(node, "name", ""))
            if m is not None and m.node is node:
                return m
        for key, cand in mi.top_funcs.items():
            if cand.node is node:
                return cand
        fi = FuncInfo(node=node, module=mi,
                      qualname=getattr(node, "name", "<lambda>"))
        mi.top_funcs[f"<nested>{id(node)}"] = fi
        self._remember(fi)
        return fi

    def _jit_target_funcs(self, mi: ModuleInfo, target: ast.AST
                          ) -> List[FuncInfo]:
        """Functions actually traced by `jax.jit(target, ...)`."""
        if isinstance(target, ast.Name):
            fi = self._find_def_anywhere(mi, target.id)
            if fi is not None:
                return [fi]
            # imported (possibly re-exported) function
            return [self.func(fid)
                    for fid in self.resolve_name(mi, target.id)]
        if isinstance(target, ast.Lambda):
            fi = FuncInfo(node=target, module=mi, qualname="<lambda>")
            mi.top_funcs[f"<lambda>{id(target)}"] = fi
            self._remember(fi)
            return [fi]
        if isinstance(target, ast.Call):
            # jit(factory(...)): the factory's returned local functions
            # are the traced entries (inference/predictor.py _program)
            out: List[FuncInfo] = []
            for fid in self._resolve_value_ref(mi, target.func, None, None):
                for rid in self.returned_funcs(self.func(fid)):
                    out.append(self.func(rid))
            return out
        return []

    def _find_def_anywhere(self, mi: ModuleInfo, name: str
                           ) -> Optional[FuncInfo]:
        if name in mi.top_funcs:
            return mi.top_funcs[name]
        for node in cached_walk(mi.pf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == name:
                return self._func_for_def(mi, node)
        return None

    def _is_jit_name(self, mi: ModuleInfo, expr: ast.AST) -> bool:
        return mi.dotted_of(expr) in _JIT_NAMES

    def _jit_decorator_statics(self, mi: ModuleInfo, dec: ast.AST,
                               fn: ast.AST) -> Optional[Set[str]]:
        """None if `dec` is not a jit decorator; else the static param
        names it declares."""
        if self._is_jit_name(mi, dec):
            return set()
        if isinstance(dec, ast.Call):
            dotted = mi.dotted_of(dec.func)
            if dotted in _PARTIAL_NAMES and dec.args \
                    and self._is_jit_name(mi, dec.args[0]):
                return self._static_names_of(mi, dec, fn)
            if self._is_jit_name(mi, dec.func):
                return self._static_names_of(mi, dec, fn)
        return None

    def _static_names_of(self, mi: ModuleInfo, call: ast.Call,
                         fn: ast.AST) -> Set[str]:
        out: Set[str] = set()
        params = []
        a = fn.args
        params += [p.arg for p in getattr(a, "posonlyargs", [])]
        params += [p.arg for p in a.args]
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for v in cached_walk(kw.value):
                    if isinstance(v, ast.Constant) and isinstance(v.value,
                                                                  str):
                        out.add(v.value)
            elif kw.arg == "static_argnums":
                for v in cached_walk(kw.value):
                    if isinstance(v, ast.Constant) and isinstance(v.value,
                                                                  int):
                        if 0 <= v.value < len(params):
                            out.add(params[v.value])
        return out

    # ---- value bindings / function references --------------------------

    def _resolve_bindings(self) -> None:
        """Module-level `name = <expr referencing functions>` bindings,
        iterated so chains across modules settle (g = jax.jit(f) in one
        module, re-exported and re-bound in another)."""
        for _ in range(4):
            changed = False
            for mi in self.modules.values():
                for name, exprs in mi.binding_exprs.items():
                    refs: Set[int] = set()
                    for e in exprs:
                        refs |= self.collect_refs(mi, e, None, None)
                    cur = mi.value_bindings.setdefault(name, set())
                    if refs - cur:
                        cur |= refs
                        changed = True
            if not changed:
                break

    def _collect_class_attrs(self) -> None:
        """`self.attr = <expr>` / `self.attr[k] = <expr>` anywhere in a
        class's methods (plus class-body assignments) -> attr_funcs."""
        for _ in range(4):
            changed = False
            for mi in self.modules.values():
                for ci in mi.top_classes.values():
                    for item in ci.node.body:
                        if isinstance(item, ast.Assign):
                            refs = self.collect_refs(mi, item.value, ci,
                                                     None)
                            for t in item.targets:
                                if isinstance(t, ast.Name) and refs:
                                    cur = ci.attr_funcs.setdefault(
                                        t.id, set())
                                    if refs - cur:
                                        cur |= refs
                                        changed = True
                    for node in cached_walk(ci.node):
                        if not isinstance(node, ast.Assign):
                            continue
                        refs = None
                        for t in node.targets:
                            attr = self._self_attr_target(t)
                            if attr is None:
                                continue
                            if isinstance(node.value, ast.Call) \
                                    and attr not in ci.attr_types:
                                dotted = mi.dotted_of(node.value.func)
                                if dotted:
                                    ci.attr_types[attr] = dotted
                            if refs is None:
                                refs = self.collect_refs(
                                    mi, node.value, ci, None)
                            if refs:
                                cur = ci.attr_funcs.setdefault(attr, set())
                                if refs - cur:
                                    cur |= refs
                                    changed = True
            if not changed:
                break

    @staticmethod
    def _self_attr_target(t: ast.AST) -> Optional[str]:
        """`self.attr` or `self.attr[k]` assignment target -> attr."""
        if isinstance(t, ast.Subscript):
            t = t.value
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id in ("self", "cls"):
            return t.attr
        return None

    def collect_refs(self, mi: ModuleInfo, expr: Optional[ast.AST],
                     owner_class: Optional[ClassInfo],
                     local_map: Optional[Dict[str, Set[int]]]) -> Set[int]:
        """Function references appearing in VALUE position inside `expr`
        (not in call position), looking through jit wrappers, containers,
        conditionals, and in-package factory returns."""
        out: Set[int] = set()
        if expr is None:
            return out
        if isinstance(expr, (ast.Name, ast.Attribute, ast.Subscript)):
            return self._resolve_value_ref(mi, expr, owner_class,
                                           local_map)
        if isinstance(expr, ast.Call):
            dotted = mi.dotted_of(expr.func) or ""
            if dotted in _JIT_NAMES or (dotted in _PARTIAL_NAMES
                                        and expr.args):
                return self.collect_refs(mi, expr.args[0], owner_class,
                                         local_map)
            # in-package factory: its returned local functions
            for fid in self._resolve_value_ref(mi, expr.func, owner_class,
                                               local_map):
                out |= self.returned_funcs(self.func(fid))
            # wrappers (RecompileDetector(fn, ...)): references in args
            for a in list(expr.args) + [kw.value for kw in expr.keywords]:
                out |= self.collect_refs(mi, a, owner_class, local_map)
            return out
        if isinstance(expr, ast.Dict):
            for v in expr.values:
                out |= self.collect_refs(mi, v, owner_class, local_map)
            return out
        if isinstance(expr, (ast.List, ast.Tuple, ast.Set)):
            for v in expr.elts:
                out |= self.collect_refs(mi, v, owner_class, local_map)
            return out
        if isinstance(expr, ast.IfExp):
            return (self.collect_refs(mi, expr.body, owner_class,
                                      local_map)
                    | self.collect_refs(mi, expr.orelse, owner_class,
                                        local_map))
        if isinstance(expr, ast.BoolOp):
            for v in expr.values:
                out |= self.collect_refs(mi, v, owner_class, local_map)
            return out
        return out

    def _resolve_value_ref(self, mi: ModuleInfo, expr: ast.AST,
                           owner_class: Optional[ClassInfo],
                           local_map: Optional[Dict[str, Set[int]]]
                           ) -> Set[int]:
        """A Name/Attribute/Subscript in value position -> functions it
        may denote."""
        if isinstance(expr, ast.Subscript):
            # container[key] -> the container's members
            return self._resolve_value_ref(mi, expr.value, owner_class,
                                           local_map)
        if isinstance(expr, ast.Name):
            if local_map and expr.id in local_map:
                return set(local_map[expr.id])
            return self.resolve_name(mi, expr.id)
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) \
                    and expr.value.id in ("self", "cls") \
                    and owner_class is not None:
                m = owner_class.find_method(expr.attr)
                out = {id(m)} if m is not None else set()
                return out | owner_class.find_attr_funcs(expr.attr)
            # ClassName.method
            ci = self._resolve_class(mi, expr.value)
            if ci is not None:
                m = ci.find_method(expr.attr)
                return {id(m)} if m is not None else set()
            # module.func through imports (plain `import pkg.mod` and the
            # `from . import mod` module-as-attribute form)
            if isinstance(expr.value, ast.Name):
                tgt = self._imported_module(mi, expr.value.id)
                if tgt is not None:
                    return self.resolve_name(tgt, expr.attr)
        return set()

    def _imported_module(self, mi: ModuleInfo,
                         name: str) -> Optional[ModuleInfo]:
        """The in-package module a bare name denotes: `import x.y` binds
        x, `from . import mod` binds mod as an attribute of the
        package."""
        imp = mi.imports.get(name)
        if not imp:
            return None
        if imp[1] is None:
            return self.modules.get(imp[0])
        return self.modules.get(imp[0] + "." + imp[1])

    def resolve_name(self, mi: ModuleInfo, name: str,
                     _seen: Optional[Set[Tuple[str, str]]] = None
                     ) -> Set[int]:
        """A bare name in `mi` -> functions it denotes, following
        defs, value bindings, and import/re-export chains."""
        _seen = _seen or set()
        key = (mi.dotted, name)
        if key in _seen:
            return set()
        _seen.add(key)
        if name in mi.top_funcs:
            return {id(mi.top_funcs[name])}
        out: Set[int] = set(mi.value_bindings.get(name, ()))
        imp = mi.imports.get(name)
        if imp:
            mod, attr = imp
            tgt = self.modules.get(mod)
            if tgt is not None and attr:
                out |= self.resolve_name(tgt, attr, _seen)
        return out

    def returned_funcs(self, fi: FuncInfo) -> Set[int]:
        """Locally-defined functions `fi` may return (factory pattern:
        make_sharded_wave_fn returns `call`)."""
        cached = getattr(fi, "_returned", None)
        if cached is not None:
            return cached
        fi._returned = set()  # type: ignore[attr-defined]  # cycle guard
        out: Set[int] = set()
        nested: Dict[str, ast.AST] = {}
        if not isinstance(fi.node, ast.Lambda):
            for node in cached_walk(fi.node):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node is not fi.node:
                    nested.setdefault(node.name, node)
            for node in cached_walk(fi.node):
                if isinstance(node, ast.Return) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id in nested:
                    out.add(id(self._func_for_def(fi.module,
                                                  nested[node.value.id])))
        fi._returned = out  # type: ignore[attr-defined]
        return out

    # ---- v3: concurrency roots ----------------------------------------
    # The reliability stack's hazards live in code that runs OUTSIDE the
    # main thread's program order: signal handlers (`signal.signal(sig,
    # fn)`), watchdog/worker threads (`threading.Thread(target=fn)`), and
    # callables shipped to another thread for deferred execution
    # (`writer.submit(self._append, line)`).  These are new ROOT KINDS:
    # the concurrency rules walk each root's reachable set the same way
    # the jit rules walk jit roots.

    def _named_funcs(self) -> List[FuncInfo]:
        out: List[FuncInfo] = []
        for mi in self.modules.values():
            out.extend(mi.top_funcs.values())
            for ci in mi.top_classes.values():
                out.extend(ci.methods.values())
        return out

    def _refs_with_nested(self, mi: ModuleInfo,
                          owner: Optional[ClassInfo],
                          nested: Dict[str, ast.AST],
                          expr: ast.AST) -> Set[int]:
        """Function refs `expr` may denote, nested defs included (a
        handler or thread target is very often a closure)."""
        if isinstance(expr, ast.Name) and expr.id in nested:
            return {id(self._func_for_def(mi, nested[expr.id]))}
        return set(self.collect_refs(mi, expr, owner, None))

    def concurrency_roots(self) -> Tuple[List[FuncInfo], List[FuncInfo]]:
        """(handler_roots, thread_roots) of the whole package.

        * handler roots: callables registered via `signal.signal(sig,
          fn)` (and any callable argument of `faulthandler.register`);
        * thread roots: `threading.Thread(target=fn)` targets, plus
          callables passed to a `.submit(...)` call — the AsyncWriter
          deferred-execution shape, where the callee runs on the worker
          thread though no Thread() names it.
        """
        cached = getattr(self, "_concur_roots", None)
        if cached is not None:
            return cached
        handler_ids: Set[int] = set()
        thread_ids: Set[int] = set()

        def scan(mi, owner, nested, body_root):
            for node in cached_walk(body_root):
                if not isinstance(node, ast.Call):
                    continue
                dotted = mi.dotted_of(node.func) or ""
                tail = dotted.rsplit(".", 1)[-1]
                args = list(node.args) + [kw.value for kw in node.keywords
                                          if kw.arg != "args"]
                if dotted in ("signal.signal", "faulthandler.register"):
                    for a in args:
                        handler_ids.update(
                            self._refs_with_nested(mi, owner, nested, a))
                elif tail == "Thread" and dotted.startswith(
                        ("threading.", "Thread")):
                    target = None
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = kw.value
                    if target is None and node.args:
                        target = node.args[1] if len(node.args) > 1 \
                            else None
                    if target is not None:
                        thread_ids.update(self._refs_with_nested(
                            mi, owner, nested, target))
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "submit":
                    for a in args:
                        thread_ids.update(self._refs_with_nested(
                            mi, owner, nested, a))

        for fi in self._named_funcs():
            if fi.node is None or isinstance(fi.node, ast.Lambda):
                continue
            nested = {n.name: n for n in cached_walk(fi.node)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
                      and n is not fi.node}
            scan(fi.module, fi.owner_class, nested, fi.node)
        for mi in self.modules.values():
            if mi.pf.tree is None:
                continue
            for stmt in mi.pf.tree.body:
                if not isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef)):
                    scan(mi, None, {}, stmt)

        roots = ([self.func(i) for i in handler_ids],
                 [self.func(i) for i in thread_ids])
        self._concur_roots = roots  # type: ignore[attr-defined]
        return roots

    # method names owned by stdlib containers/strings/files: a duck
    # step through `.update()` or `.get()` would wire dict calls to
    # Booster.update and explode the reach with false edges
    _DUCK_SKIP = {
        "update", "get", "pop", "popitem", "keys", "values", "items",
        "setdefault", "clear", "copy", "append", "appendleft", "extend",
        "insert", "remove", "sort", "reverse", "add", "discard", "union",
        "split", "rsplit", "splitlines", "strip", "lstrip", "rstrip",
        "join", "format", "encode", "decode", "startswith", "endswith",
        "replace", "count", "index", "lower", "upper", "title", "tell",
        "seek", "read", "readline", "readlines", "search", "match",
        "group", "groups", "astype", "reshape", "tolist", "item", "sum",
        "mean", "min", "max", "any", "all",
    }

    def methods_named(self, name: str) -> List[FuncInfo]:
        """Every in-package method with this name — the duck-typed
        fallback resolution the concurrency reach uses for method calls
        on objects whose class the expression does not reveal
        (`_current.emit(...)`, `w.flush(...)`).  Over-approximating
        reach is the right bias for a safety rule; names stdlib
        containers own (`_DUCK_SKIP`) and names shared by more than a
        handful of classes are too ambiguous to step through."""
        table = getattr(self, "_methods_by_name", None)
        if table is None:
            table = {}
            for mi in self.modules.values():
                for ci in mi.top_classes.values():
                    for mname, fi in ci.methods.items():
                        table.setdefault(mname, []).append(fi)
            self._methods_by_name = table  # type: ignore[attr-defined]
        return list(table.get(name, ()))

    def reachable_from(self, seeds: List[FuncInfo],
                       duck: bool = True) -> Dict[int, FuncInfo]:
        """BFS over the call graph from `seeds`: resolved calls, calls to
        nested defs, and (with `duck`) name-based method fallback for
        receivers the v2 resolution cannot type.  Returns
        {id(FuncInfo): FuncInfo} of every function in the closure."""
        seen: Dict[int, FuncInfo] = {}
        work = list(seeds)
        while work:
            fi = work.pop()
            if fi is None or id(fi) in seen or fi.node is None:
                continue
            seen[id(fi)] = fi
            mi, owner = fi.module, fi.owner_class
            if isinstance(fi.node, ast.Lambda):
                nested: Dict[str, ast.AST] = {}
            else:
                nested = {n.name: n for n in cached_walk(fi.node)
                          if isinstance(n, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))
                          and n is not fi.node}
            for node in cached_walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Name) \
                        and node.func.id in nested:
                    work.append(self._func_for_def(
                        mi, nested[node.func.id]))
                    continue
                resolved = self.resolve_call_multi(mi, node.func, owner)
                for callee, _off in resolved:
                    work.append(callee)
                if resolved or not duck \
                        or not isinstance(node.func, ast.Attribute) \
                        or node.func.attr.startswith("__") \
                        or node.func.attr in self._DUCK_SKIP:
                    continue
                base = node.func.value
                # a module attribute (np.asarray) is not a duck method
                if isinstance(base, ast.Name) and base.id in mi.imports:
                    continue
                # a self-attribute whose constructor is known and is NOT
                # an in-package class is a stdlib instance (Thread, file,
                # Queue): duck-stepping into package methods of the same
                # name (`self._thread.start()` -> RunGuard.start) would
                # be a false edge
                if isinstance(base, ast.Attribute) \
                        and isinstance(base.value, ast.Name) \
                        and base.value.id in ("self", "cls") \
                        and owner is not None:
                    ctor = owner.find_attr_type(base.attr)
                    if ctor is not None and not ctor.startswith(
                            self.ctx.package_name + "."):
                        head = ctor.split(".", 1)[0]
                        if head not in mi.top_classes:
                            continue
                cands = self.methods_named(node.func.attr)
                if 0 < len(cands) <= 4:
                    work.extend(cands)
        return seen

    # ---- call resolution ----------------------------------------------

    def resolve_call(self, mi: ModuleInfo, func: ast.AST
                     ) -> Optional[FuncInfo]:
        """v1-compatible single-target resolution (direct calls only)."""
        if isinstance(func, ast.Name):
            if func.id in mi.top_funcs:
                return mi.top_funcs[func.id]
            imp = mi.imports.get(func.id)
            if imp:
                mod, attr = imp
                tgt = self.modules.get(mod)
                if tgt and attr and attr in tgt.top_funcs:
                    return tgt.top_funcs[attr]
        elif isinstance(func, ast.Attribute) and isinstance(func.value,
                                                            ast.Name):
            tgt = self._imported_module(mi, func.value.id)
            if tgt and func.attr in tgt.top_funcs:
                return tgt.top_funcs[func.attr]
        return None

    def resolve_call_multi(self, mi: ModuleInfo, func: ast.AST,
                           owner_class: Optional[ClassInfo] = None,
                           local_map: Optional[Dict[str, Set[int]]] = None,
                           param_funcs: Optional[Dict[str, Set[int]]] = None
                           ) -> List[Tuple[FuncInfo, int]]:
        """All in-package functions a call's func expression may reach,
        as (callee, param_offset) — offset 1 for bound-method calls
        (`self.m(...)` binds args from the second parameter on)."""
        out: List[Tuple[FuncInfo, int]] = []
        seen: Set[int] = set()

        def add(fid: int, offset: int) -> None:
            if fid not in seen:
                seen.add(fid)
                out.append((self.func(fid), offset))

        if isinstance(func, ast.Name):
            if param_funcs and func.id in param_funcs:
                for fid in param_funcs[func.id]:
                    add(fid, 0)
                return out
            if local_map and func.id in local_map:
                for fid in local_map[func.id]:
                    fi = self.func(fid)
                    add(fid, 1 if fi.owner_class is not None else 0)
                return out
            fi = self.resolve_call(mi, func)
            if fi is not None:
                add(id(fi), 0)
                return out
            for fid in self.resolve_name(mi, func.id):
                add(fid, 0)
            return out
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls") \
                    and owner_class is not None:
                m = owner_class.find_method(func.attr)
                if m is not None:
                    add(id(m), 1)
                for fid in owner_class.find_attr_funcs(func.attr):
                    fi = self.func(fid)
                    # a bound method stored in the table still binds
                    # args after self; plain functions bind from 0
                    add(fid, 1 if fi.owner_class is not None else 0)
                return out
            ci = self._resolve_class(mi, base)
            if ci is not None:
                m = ci.find_method(func.attr)
                if m is not None:
                    add(id(m), 0)  # Cls.m(obj, ...) binds from `self`
                return out
            fi = self.resolve_call(mi, func)
            if fi is not None:
                add(id(fi), 0)
            return out
        if isinstance(func, ast.Subscript):
            # TABLE[key](...) — every container member
            for fid in self._resolve_value_ref(mi, func, owner_class,
                                               local_map):
                fi = self.func(fid)
                add(fid, 1 if fi.owner_class is not None
                    and isinstance(func.value, ast.Attribute) else 0)
            return out
        return out


def walk_scope(root: ast.AST):
    """Yield `root` and every descendant that belongs to root's lexical
    scope — nested FunctionDef/Lambda nodes are yielded (they are bound
    in this scope) but their interiors are not."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                yield child
                # decorators/defaults evaluate in the enclosing scope
                for d in getattr(child, "decorator_list", []):
                    stack.append(d)
                for d in child.args.defaults + [
                        x for x in child.args.kw_defaults if x]:
                    stack.append(d)
            else:
                stack.append(child)


class Scope:
    """One lexical scope (function body) with Python shadowing rules: a
    name assigned anywhere in the scope is local throughout it."""

    def __init__(self, node: ast.AST, parent: Optional["Scope"]):
        self.node = node
        self.parent = parent
        self.assigned: Set[str] = set()
        self.tainted: Set[str] = set()
        a = node.args
        for p in (list(getattr(a, "posonlyargs", [])) + list(a.args)
                  + list(a.kwonlyargs)):
            self.assigned.add(p.arg)
        if a.vararg:
            self.assigned.add(a.vararg.arg)
        if a.kwarg:
            self.assigned.add(a.kwarg.arg)
        if not isinstance(node, ast.Lambda):
            self._collect_assigned()

    def _collect_assigned(self) -> None:
        for n in walk_scope(self.node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.assigned.add(n.name)
            elif isinstance(n, ast.Assign):
                for t in n.targets:
                    self._bind(t)
            elif isinstance(n, (ast.AnnAssign, ast.AugAssign)):
                self._bind(n.target)
            elif isinstance(n, ast.NamedExpr):
                self._bind(n.target)
            elif isinstance(n, ast.For):
                self._bind(n.target)
            elif isinstance(n, ast.withitem):
                if n.optional_vars is not None:
                    self._bind(n.optional_vars)
            elif isinstance(n, ast.comprehension):
                self._bind(n.target)
            elif isinstance(n, ast.ExceptHandler) and n.name:
                self.assigned.add(n.name)
            elif isinstance(n, (ast.Import, ast.ImportFrom)):
                for al in n.names:
                    self.assigned.add(
                        (al.asname or al.name).split(".")[0])
            elif isinstance(n, (ast.Global, ast.Nonlocal)):
                for name in n.names:
                    self.assigned.discard(name)

    def _bind(self, target: ast.AST) -> None:
        for n in cached_walk(target):
            if isinstance(n, ast.Name):
                self.assigned.add(n.id)

    def owner_of(self, name: str) -> Optional["Scope"]:
        s = self
        while s is not None:
            if name in s.assigned:
                return s
            s = s.parent
        return None

    def is_tainted(self, name: str) -> bool:
        s = self.owner_of(name)
        return s is not None and name in s.tainted

    def add_taint(self, name: str) -> bool:
        s = self.owner_of(name) or self
        if name in s.tainted:
            return False
        s.tainted.add(name)
        return True


class TaintWalker:
    """Lexically-scoped taint propagation over one jit-rooted function
    (including its nested defs).  Violations are collected by the rules
    via `taint(expr)`; callee taints are reported back for the
    cross-module fixpoint."""

    def __init__(self, index: PackageIndex, fi: FuncInfo):
        self.index = index
        self.mi = fi.module
        self.fi = fi
        self.owner_class = fi.owner_class
        # scope tree + node -> owning scope map
        self.scopes: List[Scope] = []
        self.scope_of_def: Dict[int, Scope] = {}
        self.node_scope: Dict[int, Scope] = {}
        self._build_scopes(fi.node, None)
        root = self.scope_of_def[id(fi.node)]
        for name in fi.tainted_params:
            root.tainted.add(name)
        # nested function name -> def node (first definition wins)
        self.nested: Dict[str, ast.AST] = {}
        for node in cached_walk(fi.node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fi.node:
                name = getattr(node, "name", None)
                if name and name not in self.nested:
                    self.nested[name] = node
        # function-valued local bindings (tables built in this function)
        self.local_funcs: Dict[str, Set[int]] = {}
        if not isinstance(fi.node, ast.Lambda):
            for node in cached_walk(fi.node):
                if isinstance(node, ast.Assign):
                    refs = index.collect_refs(self.mi, node.value,
                                              self.owner_class, None)
                    if refs:
                        for t in node.targets:
                            tt = t.value if isinstance(t, ast.Subscript) \
                                else t
                            if isinstance(tt, ast.Name):
                                self.local_funcs.setdefault(
                                    tt.id, set()).update(refs)
        # taints discovered for in-package callees: FuncInfo -> set(param)
        self.callee_taints: Dict[int, Tuple[FuncInfo, Set[str]]] = {}
        # fixpoint-relevant statements, collected ONCE per walker: the
        # env fixpoint used to re-walk the whole AST every iteration,
        # which dominated the cold-lint profile
        self._fix_nodes: List[Tuple[ast.AST, Scope]] = []
        for scope in self.scopes:
            for node in walk_scope(scope.node):
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign, ast.NamedExpr,
                                     ast.For, ast.withitem, ast.Call)):
                    self._fix_nodes.append((node, scope))

    def _build_scopes(self, node: ast.AST, parent: Optional[Scope]) -> None:
        scope = Scope(node, parent)
        self.scopes.append(scope)
        self.scope_of_def[id(node)] = scope
        for n in walk_scope(node):
            self.node_scope.setdefault(id(n), scope)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and n is not node:
                self._build_scopes(n, scope)

    # ---- expression taint ----

    def taint(self, e: Optional[ast.AST], scope: Optional[Scope] = None
              ) -> bool:
        """Is `e` (a node anywhere in this root's tree) possibly a
        tracer?  Scope is looked up from the node when not given."""
        if e is None or isinstance(e, ast.Constant):
            return False
        if scope is None:
            scope = self.node_scope.get(id(e))
            if scope is None:
                return False
        return self._taint(e, scope)

    def _taint(self, e: Optional[ast.AST], scope: Scope) -> bool:
        taint = lambda x: self._taint(x, scope)  # noqa: E731
        if e is None or isinstance(e, ast.Constant):
            return False
        if isinstance(e, ast.Name):
            return scope.is_tainted(e.id)
        if isinstance(e, ast.Attribute):
            if e.attr in STATIC_ATTRS:
                return False
            return taint(e.value)
        if isinstance(e, ast.Subscript):
            return taint(e.value) or taint(e.slice)
        if isinstance(e, ast.Call):
            dotted = self.mi.dotted_of(e.func)
            if dotted in STATIC_CALLS:
                return False
            args = list(e.args) + [kw.value for kw in e.keywords]
            if any(taint(a) for a in args):
                return True
            # a method call on a tracer returns a tracer (x.sum(),
            # x.astype(...)); module functions (jnp.sum) are covered by
            # their arguments above
            return isinstance(e.func, ast.Attribute) and taint(e.func)
        if isinstance(e, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                return False
            return taint(e.left) or any(taint(c)
                                             for c in e.comparators)
        if isinstance(e, (ast.BinOp,)):
            return taint(e.left) or taint(e.right)
        if isinstance(e, ast.BoolOp):
            return any(taint(v) for v in e.values)
        if isinstance(e, ast.UnaryOp):
            return taint(e.operand)
        if isinstance(e, ast.IfExp):
            return (taint(e.test) or taint(e.body)
                    or taint(e.orelse))
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(taint(x) for x in e.elts)
        if isinstance(e, ast.Dict):
            return any(taint(x) for x in e.keys if x is not None) \
                or any(taint(x) for x in e.values)
        if isinstance(e, ast.Starred):
            return taint(e.value)
        if isinstance(e, ast.NamedExpr):
            return taint(e.value)
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return (taint(e.elt)
                    or any(taint(g.iter) for g in e.generators))
        if isinstance(e, ast.DictComp):
            return (taint(e.key) or taint(e.value)
                    or any(taint(g.iter) for g in e.generators))
        if isinstance(e, ast.Slice):
            return any(taint(x) for x in (e.lower, e.upper, e.step))
        return False

    # ---- environment fixpoint ----

    def _changed(self) -> int:
        return sum(len(s.tainted) for s in self.scopes)

    def _bind_names(self, target: ast.AST, scope: Scope) -> None:
        for node in cached_walk(target):
            if isinstance(node, ast.Name):
                scope.add_taint(node.id)

    def _funcs_of_expr(self, node: ast.AST) -> Set[int]:
        """Function references an argument expression may denote (for
        higher-order parameter binding)."""
        return self.index.collect_refs(self.mi, node, self.owner_class,
                                       self.local_funcs)

    def _taint_callee_params(self, node: ast.AST, first_k: int) -> None:
        """Mark the first `first_k` parameters of a locally-nested or
        in-package function as tainted (lax/vmap calling contracts)."""
        name = node.id if isinstance(node, ast.Name) else None
        fn = self.nested.get(name) if name else None
        if fn is not None:
            child = self.scope_of_def.get(id(fn))
            if child is not None:
                for p in fn.args.args[:first_k]:
                    child.tainted.add(p.arg)
            return
        for fid in self._funcs_of_expr(node):
            fi = self.index.func(fid)
            names = fi.param_names[:first_k]
            self._record_callee(fi, set(names) - fi.static_params)

    def _record_callee(self, fi: FuncInfo, tainted: Set[str]) -> None:
        tainted = tainted - fi.static_params
        key = id(fi)
        if key in self.callee_taints:
            self.callee_taints[key][1].update(tainted)
        else:
            # an empty edge still puts the callee in the reachable set
            self.callee_taints[key] = (fi, set(tainted))

    def _taint_def_params(self, fn: ast.AST, e: ast.Call,
                          scope: Scope) -> None:
        """Bind a direct call's tainted args onto a nested def's params
        (in its own scope)."""
        child = self.scope_of_def.get(id(fn))
        if child is None:
            return
        params = [p.arg for p in fn.args.args]
        for i, a in enumerate(e.args):
            if isinstance(a, ast.Starred):
                continue
            if i < len(params) and self._taint(a, scope):
                child.tainted.add(params[i])
        for kw in e.keywords:
            if kw.arg and kw.arg in params and self._taint(kw.value, scope):
                child.tainted.add(kw.arg)

    def _bind_call_args(self, fi: FuncInfo, offset: int, e: ast.Call,
                        scope: Scope) -> None:
        """Record tainted params and function-valued args for one
        resolved in-package callee."""
        if fi.node is self.fi.node:
            return
        params = fi.param_names
        tainted: Set[str] = set()
        func_bound = False
        for i, a in enumerate(e.args):
            if isinstance(a, ast.Starred):
                continue
            pi = i + offset
            if pi >= len(params):
                continue
            if self._taint(a, scope):
                tainted.add(params[pi])
            refs = self._funcs_of_expr(a)
            if refs:
                cur = fi.param_funcs.setdefault(params[pi], set())
                if refs - cur:
                    cur |= refs
                    func_bound = True
        for kw in e.keywords:
            if not kw.arg:
                continue
            if self._taint(kw.value, scope):
                tainted.add(kw.arg)
            refs = self._funcs_of_expr(kw.value)
            if refs and kw.arg in params:
                cur = fi.param_funcs.setdefault(kw.arg, set())
                if refs - cur:
                    cur |= refs
                    func_bound = True
        if func_bound:
            self._param_funcs_changed = True
        self._record_callee(fi, tainted)

    def _propagate_call(self, e: ast.Call, scope: Scope) -> None:
        """Taint flow into nested functions / package callees."""
        dotted = self.mi.dotted_of(e.func) or ""
        tail = dotted.rsplit(".", 1)[-1]
        # lax higher-order functions taking a function argument
        if dotted.startswith(("jax.lax.", "lax.")) and tail in _LAX_HOF:
            for arg_i, k in _LAX_HOF[tail]:
                if arg_i < len(e.args):
                    self._taint_callee_params(e.args[arg_i], k)
            if tail == "switch" and len(e.args) >= 2 \
                    and isinstance(e.args[1], (ast.List, ast.Tuple)):
                for elt in e.args[1].elts:
                    self._taint_callee_params(elt, 99)
            return
        # jax.vmap(f)(...) etc: the func is itself a call whose first
        # arg names a function; its operands are traced
        if isinstance(e.func, ast.Call):
            inner = self.mi.dotted_of(e.func.func) or ""
            if inner.rsplit(".", 1)[-1] in ("vmap", "pmap", "checkpoint",
                                            "remat", "shard_map"):
                if e.func.args:
                    self._taint_callee_params(e.func.args[0], 99)
            return
        # direct call to a nested def: bind args -> params
        if isinstance(e.func, ast.Name) and e.func.id in self.nested:
            self._taint_def_params(self.nested[e.func.id], e, scope)
            return
        # calls through a tainted-parameter function value, methods,
        # containers, bindings, and plain package functions
        params = {p: f for p, f in self.fi.param_funcs.items()}
        for fi, offset in self.index.resolve_call_multi(
                self.mi, e.func, self.owner_class, self.local_funcs,
                params):
            self._bind_call_args(fi, offset, e, scope)

    def run_env_fixpoint(self, max_iter: int = 16) -> None:
        self._param_funcs_changed = False
        for _ in range(max_iter):
            before = self._changed()
            for node, scope in self._fix_nodes:
                if isinstance(node, ast.Assign):
                    if self._taint(node.value, scope):
                        for t in node.targets:
                            self._bind_names(t, scope)
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    if node.value is not None \
                            and self._taint(node.value, scope):
                        self._bind_names(node.target, scope)
                elif isinstance(node, ast.NamedExpr):
                    if self._taint(node.value, scope):
                        self._bind_names(node.target, scope)
                elif isinstance(node, ast.For):
                    if self._taint(node.iter, scope):
                        self._bind_names(node.target, scope)
                elif isinstance(node, ast.withitem):
                    if node.optional_vars is not None \
                            and self._taint(node.context_expr, scope):
                        self._bind_names(node.optional_vars, scope)
                elif isinstance(node, ast.Call):
                    self._propagate_call(node, scope)
            if self._changed() == before:
                break


def build_reachable(index: PackageIndex) -> List[FuncInfo]:
    """Fixpoint over the call graph: analyze every jit root, propagate
    parameter taints into in-package callees, repeat until stable.
    Returns the analyzed FuncInfos (roots + jit-reachable callees) with
    `tainted_params` filled in; walkers are cached on each FuncInfo as
    `_walker` for the rules to consume."""
    work: List[FuncInfo] = []
    for mi in index.modules.values():
        roots = list(mi.top_funcs.values())
        for ci in mi.top_classes.values():
            roots += list(ci.methods.values())
        for fi in roots:
            if fi.jit_root:
                fi.tainted_params = (set(fi.param_names)
                                     - fi.static_params - {"self", "cls"})
                work.append(fi)
    analyzed: Dict[int, FuncInfo] = {}
    for _ in range(20):  # cross-function fixpoint
        changed = False
        queue = list(work) + [fi for fi in analyzed.values()
                              if not fi.jit_root]
        seen: Set[int] = set()
        for fi in queue:
            if id(fi) in seen or fi.node is None:
                continue
            seen.add(id(fi))
            walker = getattr(fi, "_walker", None)
            if walker is None:
                walker = TaintWalker(index, fi)
            else:
                # reuse the walker across outer rounds (scope tree and
                # statement lists are immutable); only the root taints
                # grew since last round
                root = walker.scope_of_def[id(fi.node)]
                root.tainted |= fi.tainted_params
            walker.run_env_fixpoint()
            if walker._param_funcs_changed:
                changed = True
            fi._walker = walker  # type: ignore[attr-defined]
            analyzed[id(fi)] = fi
            for _, (callee, taints) in walker.callee_taints.items():
                new = taints - callee.tainted_params
                if new or id(callee) not in analyzed:
                    callee.tainted_params |= new
                    if id(callee) not in analyzed:
                        analyzed[id(callee)] = callee
                    changed = True
        if not changed:
            break
    return list(analyzed.values())
