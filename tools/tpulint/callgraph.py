"""Static jit call graph + parameter-taint analysis for tpulint.

The device-code rules (no-host-sync-in-jit, no-tracer-branch) need to
know which code runs under `jax.jit` tracing and which values are
tracers there.  Both are approximated statically:

* **Roots**: every function wrapped in jit anywhere in the package —
  `@jax.jit`, `@functools.partial(jax.jit, static_argnames=...)`, and
  the assignment form `f = jax.jit(g, ...)` where `g` is a local
  function.  `static_argnames`/`static_argnums` are honored: those
  parameters are Python values at trace time, and branching on them is
  exactly how static configuration is supposed to work.

* **Call graph**: from each root, calls to other functions defined in
  the package (same module or via `from ..mod import name` imports) are
  resolved and the callee is analyzed too, with its parameters tainted
  per call site (a traced argument taints the bound parameter; a static
  one does not).  Iterated to a fixpoint, so taint flows through helper
  layers (grow_tree -> find_best_split -> leaf_gain).

* **Taint**: within one root, a flat name->tainted environment seeded by
  the non-static parameters.  Assignments propagate taint through
  expressions; `.shape`/`.ndim`/`.dtype`/`.size` access yields a STATIC
  value even on a tracer (that's how jit code legitimately branches on
  geometry), and `is`/`is not` comparisons are host-safe identity
  checks.  Functions passed to `lax.fori_loop`/`while_loop`/`scan`/
  `cond`/`switch` and `jax.vmap` get their parameters tainted per the
  lax calling contract (the loop index and carry are tracers).

The approximation is deliberately parameter-rooted (matching the rule
names): device constants built from static shapes are not tracked, and
dynamic dispatch (methods on objects, functions stored in containers)
is not resolved.  That keeps false positives near zero on idiomatic
JAX; the fixture tests in tests/test_tpulint.py pin the contract.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# attributes that are static (Python) values even on a tracer
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type", "sharding"}
# builtins whose call result is always a static Python value
STATIC_CALLS = {"len", "isinstance", "range", "type", "getattr", "hasattr",
                "max", "min"}

_LAX_HOF = {
    # func attr -> list of (callee_arg_index, callee_param_slice)
    # fori_loop(lo, hi, body, init): body(i, carry) — both traced
    "fori_loop": [(2, 2)],
    # while_loop(cond, body, init): each takes the traced carry
    "while_loop": [(0, 1), (1, 1)],
    # scan(f, init, xs): f(carry, x) — both traced
    "scan": [(0, 2)],
    # cond(pred, true_fn, false_fn, *operands): operands traced
    "cond": [(1, 99), (2, 99)],
    # switch(index, branches, *operands): can't see into branch lists
    # unless they are literal [name, ...] — handled separately
    "switch": [],
}


@dataclass
class FuncInfo:
    """One function definition (top-level, method, or nested)."""
    node: ast.AST                  # FunctionDef / Lambda
    module: "ModuleInfo"
    qualname: str
    jit_root: bool = False
    static_params: Set[str] = field(default_factory=set)
    # accumulated tainted parameter names (grows monotonically)
    tainted_params: Set[str] = field(default_factory=set)

    @property
    def param_names(self) -> List[str]:
        a = self.node.args
        names = [p.arg for p in getattr(a, "posonlyargs", [])]
        names += [p.arg for p in a.args]
        names += [p.arg for p in a.kwonlyargs]
        return names


class ModuleInfo:
    """Per-file index: imports and top-level functions."""

    def __init__(self, pf, package_name: str):
        self.pf = pf
        self.package_name = package_name
        # module dotted name, e.g. lightgbm_tpu.learner.grow
        parts = pf.rel[:-3].split(os.sep)
        if parts[-1] == "__init__":
            parts = parts[:-1]
        self.dotted = ".".join(parts)
        self.imports: Dict[str, Tuple[str, Optional[str]]] = {}
        self.top_funcs: Dict[str, FuncInfo] = {}
        if pf.tree is not None:
            self._index(pf.tree)

    def _resolve_relative(self, level: int, module: Optional[str]) -> str:
        base = self.dotted.split(".")
        # level=1 strips the module's own name, 2 strips one package, ...
        base = base[:len(base) - level]
        if module:
            base = base + module.split(".")
        return ".".join(base)

    def _index(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    self.imports[al.asname or al.name.split(".")[0]] = (
                        al.name, None)
            elif isinstance(node, ast.ImportFrom):
                mod = (self._resolve_relative(node.level, node.module)
                       if node.level else (node.module or ""))
                for al in node.names:
                    self.imports[al.asname or al.name] = (mod, al.name)
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.top_funcs[node.name] = FuncInfo(
                    node=node, module=self, qualname=node.name)

    def dotted_of(self, expr: ast.AST) -> Optional[str]:
        """Resolve an expression to a dotted module path, following this
        module's imports: `np.asarray` -> numpy.asarray, `jax.lax.psum`
        -> jax.lax.psum, `jit` imported from jax -> jax.jit."""
        parts: List[str] = []
        while isinstance(expr, ast.Attribute):
            parts.append(expr.attr)
            expr = expr.value
        if not isinstance(expr, ast.Name):
            return None
        base = expr.id
        if base in self.imports:
            mod, attr = self.imports[base]
            head = mod + ("." + attr if attr else "")
        else:
            head = base
        return ".".join([head] + list(reversed(parts)))


class PackageIndex:
    """All modules of the linted package + jit roots."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.modules: Dict[str, ModuleInfo] = {}
        for pf in ctx.files:
            mi = ModuleInfo(pf, ctx.package_name)
            self.modules[mi.dotted] = mi
        for mi in self.modules.values():
            self._mark_jit_roots(mi)

    # ---- jit root discovery ----

    def _mark_jit_roots(self, mi: ModuleInfo) -> None:
        if mi.pf.tree is None:
            return
        # decorated defs (any nesting depth)
        for node in ast.walk(mi.pf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    statics = self._jit_decorator_statics(mi, dec, node)
                    if statics is not None:
                        fi = mi.top_funcs.get(node.name)
                        if fi is None or fi.node is not node:
                            fi = FuncInfo(node=node, module=mi,
                                          qualname=node.name)
                            mi.top_funcs.setdefault(
                                f"<nested>{id(node)}", fi)
                        fi.jit_root = True
                        fi.static_params |= statics
            elif isinstance(node, ast.Call):
                # assignment/expression form: jax.jit(fn, ...)
                if self._is_jit_name(mi, node.func) and node.args:
                    target = node.args[0]
                    if isinstance(target, ast.Name):
                        fi = self._find_def_anywhere(mi, target.id)
                        if fi is not None:
                            fi.jit_root = True
                            fi.static_params |= self._static_names_of(
                                mi, node, fi.node)
                    elif isinstance(target, ast.Lambda):
                        fi = FuncInfo(node=target, module=mi,
                                      qualname="<lambda>")
                        fi.jit_root = True
                        fi.static_params |= self._static_names_of(
                            mi, node, target)
                        mi.top_funcs[f"<lambda>{id(target)}"] = fi

    def _find_def_anywhere(self, mi: ModuleInfo, name: str
                           ) -> Optional[FuncInfo]:
        if name in mi.top_funcs:
            return mi.top_funcs[name]
        for node in ast.walk(mi.pf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == name:
                fi = FuncInfo(node=node, module=mi, qualname=name)
                mi.top_funcs[f"<nested>{id(node)}"] = fi
                return fi
        return None

    def _is_jit_name(self, mi: ModuleInfo, expr: ast.AST) -> bool:
        dotted = mi.dotted_of(expr)
        return dotted in ("jax.jit", "jit")

    def _jit_decorator_statics(self, mi: ModuleInfo, dec: ast.AST,
                               fn: ast.AST) -> Optional[Set[str]]:
        """None if `dec` is not a jit decorator; else the static param
        names it declares."""
        if self._is_jit_name(mi, dec):
            return set()
        if isinstance(dec, ast.Call):
            dotted = mi.dotted_of(dec.func)
            if dotted in ("functools.partial", "partial") and dec.args \
                    and self._is_jit_name(mi, dec.args[0]):
                return self._static_names_of(mi, dec, fn)
            if self._is_jit_name(mi, dec.func):
                return self._static_names_of(mi, dec, fn)
        return None

    def _static_names_of(self, mi: ModuleInfo, call: ast.Call,
                         fn: ast.AST) -> Set[str]:
        out: Set[str] = set()
        params = []
        a = fn.args
        params += [p.arg for p in getattr(a, "posonlyargs", [])]
        params += [p.arg for p in a.args]
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for v in ast.walk(kw.value):
                    if isinstance(v, ast.Constant) and isinstance(v.value,
                                                                  str):
                        out.add(v.value)
            elif kw.arg == "static_argnums":
                for v in ast.walk(kw.value):
                    if isinstance(v, ast.Constant) and isinstance(v.value,
                                                                  int):
                        if 0 <= v.value < len(params):
                            out.add(params[v.value])
        return out

    # ---- cross-module function resolution ----

    def resolve_call(self, mi: ModuleInfo, func: ast.AST
                     ) -> Optional[FuncInfo]:
        """Resolve a Call's func expression to an in-package FuncInfo
        (same-module top-level functions or `from x import f` names)."""
        if isinstance(func, ast.Name):
            if func.id in mi.top_funcs:
                return mi.top_funcs[func.id]
            imp = mi.imports.get(func.id)
            if imp:
                mod, attr = imp
                tgt = self.modules.get(mod)
                if tgt and attr and attr in tgt.top_funcs:
                    return tgt.top_funcs[attr]
        elif isinstance(func, ast.Attribute) and isinstance(func.value,
                                                            ast.Name):
            imp = mi.imports.get(func.value.id)
            if imp and imp[1] is None:
                tgt = self.modules.get(imp[0])
                if tgt and func.attr in tgt.top_funcs:
                    return tgt.top_funcs[func.attr]
        return None


def walk_scope(root: ast.AST):
    """Yield `root` and every descendant that belongs to root's lexical
    scope — nested FunctionDef/Lambda nodes are yielded (they are bound
    in this scope) but their interiors are not."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                yield child
                # decorators/defaults evaluate in the enclosing scope
                for d in getattr(child, "decorator_list", []):
                    stack.append(d)
                for d in child.args.defaults + [
                        x for x in child.args.kw_defaults if x]:
                    stack.append(d)
            else:
                stack.append(child)


class Scope:
    """One lexical scope (function body) with Python shadowing rules: a
    name assigned anywhere in the scope is local throughout it."""

    def __init__(self, node: ast.AST, parent: Optional["Scope"]):
        self.node = node
        self.parent = parent
        self.assigned: Set[str] = set()
        self.tainted: Set[str] = set()
        a = node.args
        for p in (list(getattr(a, "posonlyargs", [])) + list(a.args)
                  + list(a.kwonlyargs)):
            self.assigned.add(p.arg)
        if a.vararg:
            self.assigned.add(a.vararg.arg)
        if a.kwarg:
            self.assigned.add(a.kwarg.arg)
        if not isinstance(node, ast.Lambda):
            self._collect_assigned()

    def _collect_assigned(self) -> None:
        for n in walk_scope(self.node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.assigned.add(n.name)
            elif isinstance(n, ast.Assign):
                for t in n.targets:
                    self._bind(t)
            elif isinstance(n, (ast.AnnAssign, ast.AugAssign)):
                self._bind(n.target)
            elif isinstance(n, ast.NamedExpr):
                self._bind(n.target)
            elif isinstance(n, ast.For):
                self._bind(n.target)
            elif isinstance(n, ast.withitem):
                if n.optional_vars is not None:
                    self._bind(n.optional_vars)
            elif isinstance(n, ast.comprehension):
                self._bind(n.target)
            elif isinstance(n, ast.ExceptHandler) and n.name:
                self.assigned.add(n.name)
            elif isinstance(n, (ast.Import, ast.ImportFrom)):
                for al in n.names:
                    self.assigned.add(
                        (al.asname or al.name).split(".")[0])
            elif isinstance(n, (ast.Global, ast.Nonlocal)):
                for name in n.names:
                    self.assigned.discard(name)

    def _bind(self, target: ast.AST) -> None:
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                self.assigned.add(n.id)

    def owner_of(self, name: str) -> Optional["Scope"]:
        s = self
        while s is not None:
            if name in s.assigned:
                return s
            s = s.parent
        return None

    def is_tainted(self, name: str) -> bool:
        s = self.owner_of(name)
        return s is not None and name in s.tainted

    def add_taint(self, name: str) -> bool:
        s = self.owner_of(name) or self
        if name in s.tainted:
            return False
        s.tainted.add(name)
        return True


class TaintWalker:
    """Lexically-scoped taint propagation over one jit-rooted function
    (including its nested defs).  Violations are collected by the rules
    via `taint(expr)`; callee taints are reported back for the
    cross-module fixpoint."""

    def __init__(self, index: PackageIndex, fi: FuncInfo):
        self.index = index
        self.mi = fi.module
        self.fi = fi
        # scope tree + node -> owning scope map
        self.scopes: List[Scope] = []
        self.scope_of_def: Dict[int, Scope] = {}
        self.node_scope: Dict[int, Scope] = {}
        self._build_scopes(fi.node, None)
        root = self.scope_of_def[id(fi.node)]
        for name in fi.tainted_params:
            root.tainted.add(name)
        # nested function name -> def node (first definition wins)
        self.nested: Dict[str, ast.AST] = {}
        for node in ast.walk(fi.node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fi.node:
                name = getattr(node, "name", None)
                if name and name not in self.nested:
                    self.nested[name] = node
        # taints discovered for in-package callees: FuncInfo -> set(param)
        self.callee_taints: Dict[int, Tuple[FuncInfo, Set[str]]] = {}

    def _build_scopes(self, node: ast.AST, parent: Optional[Scope]) -> None:
        scope = Scope(node, parent)
        self.scopes.append(scope)
        self.scope_of_def[id(node)] = scope
        for n in walk_scope(node):
            self.node_scope.setdefault(id(n), scope)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and n is not node:
                self._build_scopes(n, scope)

    # ---- expression taint ----

    def taint(self, e: Optional[ast.AST], scope: Optional[Scope] = None
              ) -> bool:
        """Is `e` (a node anywhere in this root's tree) possibly a
        tracer?  Scope is looked up from the node when not given."""
        if e is None or isinstance(e, ast.Constant):
            return False
        if scope is None:
            scope = self.node_scope.get(id(e))
            if scope is None:
                return False
        return self._taint(e, scope)

    def _taint(self, e: Optional[ast.AST], scope: Scope) -> bool:
        taint = lambda x: self._taint(x, scope)  # noqa: E731
        if e is None or isinstance(e, ast.Constant):
            return False
        if isinstance(e, ast.Name):
            return scope.is_tainted(e.id)
        if isinstance(e, ast.Attribute):
            if e.attr in STATIC_ATTRS:
                return False
            return taint(e.value)
        if isinstance(e, ast.Subscript):
            return taint(e.value) or taint(e.slice)
        if isinstance(e, ast.Call):
            dotted = self.mi.dotted_of(e.func)
            if dotted in STATIC_CALLS:
                return False
            args = list(e.args) + [kw.value for kw in e.keywords]
            if any(taint(a) for a in args):
                return True
            # a method call on a tracer returns a tracer (x.sum(),
            # x.astype(...)); module functions (jnp.sum) are covered by
            # their arguments above
            return isinstance(e.func, ast.Attribute) and taint(e.func)
        if isinstance(e, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                return False
            return taint(e.left) or any(taint(c)
                                             for c in e.comparators)
        if isinstance(e, (ast.BinOp,)):
            return taint(e.left) or taint(e.right)
        if isinstance(e, ast.BoolOp):
            return any(taint(v) for v in e.values)
        if isinstance(e, ast.UnaryOp):
            return taint(e.operand)
        if isinstance(e, ast.IfExp):
            return (taint(e.test) or taint(e.body)
                    or taint(e.orelse))
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(taint(x) for x in e.elts)
        if isinstance(e, ast.Dict):
            return any(taint(x) for x in e.keys if x is not None) \
                or any(taint(x) for x in e.values)
        if isinstance(e, ast.Starred):
            return taint(e.value)
        if isinstance(e, ast.NamedExpr):
            return taint(e.value)
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return (taint(e.elt)
                    or any(taint(g.iter) for g in e.generators))
        if isinstance(e, ast.DictComp):
            return (taint(e.key) or taint(e.value)
                    or any(taint(g.iter) for g in e.generators))
        if isinstance(e, ast.Slice):
            return any(taint(x) for x in (e.lower, e.upper, e.step))
        return False

    # ---- environment fixpoint ----

    def _changed(self) -> int:
        return sum(len(s.tainted) for s in self.scopes)

    def _bind_names(self, target: ast.AST, scope: Scope) -> None:
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                scope.add_taint(node.id)

    def _taint_callee_params(self, node: ast.AST, first_k: int) -> None:
        """Mark the first `first_k` parameters of a locally-nested or
        in-package function as tainted (lax/vmap calling contracts)."""
        name = node.id if isinstance(node, ast.Name) else None
        fn = self.nested.get(name) if name else None
        if fn is not None:
            child = self.scope_of_def.get(id(fn))
            if child is not None:
                for p in fn.args.args[:first_k]:
                    child.tainted.add(p.arg)
            return
        if name:
            fi = self.index.resolve_call(self.mi, node)
            if fi is not None:
                names = fi.param_names[:first_k]
                self._record_callee(fi, set(names) - fi.static_params)

    def _record_callee(self, fi: FuncInfo, tainted: Set[str]) -> None:
        tainted = tainted - fi.static_params
        key = id(fi)
        if key in self.callee_taints:
            self.callee_taints[key][1].update(tainted)
        else:
            # an empty edge still puts the callee in the reachable set
            self.callee_taints[key] = (fi, set(tainted))

    def _taint_def_params(self, fn: ast.AST, e: ast.Call,
                          scope: Scope) -> None:
        """Bind a direct call's tainted args onto a nested def's params
        (in its own scope)."""
        child = self.scope_of_def.get(id(fn))
        if child is None:
            return
        params = [p.arg for p in fn.args.args]
        for i, a in enumerate(e.args):
            if isinstance(a, ast.Starred):
                continue
            if i < len(params) and self._taint(a, scope):
                child.tainted.add(params[i])
        for kw in e.keywords:
            if kw.arg and kw.arg in params and self._taint(kw.value, scope):
                child.tainted.add(kw.arg)

    def _propagate_call(self, e: ast.Call, scope: Scope) -> None:
        """Taint flow into nested functions / package callees."""
        dotted = self.mi.dotted_of(e.func) or ""
        tail = dotted.rsplit(".", 1)[-1]
        # lax higher-order functions taking a function argument
        if dotted.startswith(("jax.lax.", "lax.")) and tail in _LAX_HOF:
            for arg_i, k in _LAX_HOF[tail]:
                if arg_i < len(e.args):
                    self._taint_callee_params(e.args[arg_i], k)
            if tail == "switch" and len(e.args) >= 2 \
                    and isinstance(e.args[1], (ast.List, ast.Tuple)):
                for elt in e.args[1].elts:
                    self._taint_callee_params(elt, 99)
            return
        # jax.vmap(f)(...) etc: the func is itself a call whose first
        # arg names a function; its operands are traced
        if isinstance(e.func, ast.Call):
            inner = self.mi.dotted_of(e.func.func) or ""
            if inner.rsplit(".", 1)[-1] in ("vmap", "pmap", "checkpoint",
                                            "remat", "shard_map"):
                if e.func.args:
                    self._taint_callee_params(e.func.args[0], 99)
            return
        # direct call to a nested def: bind args -> params
        if isinstance(e.func, ast.Name) and e.func.id in self.nested:
            self._taint_def_params(self.nested[e.func.id], e, scope)
            return
        # direct call to an in-package function
        fi = self.index.resolve_call(self.mi, e.func)
        if fi is not None and fi.node is not self.fi.node:
            params = fi.param_names
            tainted: Set[str] = set()
            for i, a in enumerate(e.args):
                if isinstance(a, ast.Starred):
                    continue
                if i < len(params) and self._taint(a, scope):
                    tainted.add(params[i])
            for kw in e.keywords:
                if kw.arg and self._taint(kw.value, scope):
                    tainted.add(kw.arg)
            self._record_callee(fi, tainted)

    def run_env_fixpoint(self, max_iter: int = 16) -> None:
        for _ in range(max_iter):
            before = self._changed()
            for scope in self.scopes:
                for node in walk_scope(scope.node):
                    if isinstance(node, ast.Assign):
                        if self._taint(node.value, scope):
                            for t in node.targets:
                                self._bind_names(t, scope)
                    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                        if node.value is not None \
                                and self._taint(node.value, scope):
                            self._bind_names(node.target, scope)
                    elif isinstance(node, ast.NamedExpr):
                        if self._taint(node.value, scope):
                            self._bind_names(node.target, scope)
                    elif isinstance(node, ast.For):
                        if self._taint(node.iter, scope):
                            self._bind_names(node.target, scope)
                    elif isinstance(node, ast.withitem):
                        if node.optional_vars is not None \
                                and self._taint(node.context_expr, scope):
                            self._bind_names(node.optional_vars, scope)
                    elif isinstance(node, ast.Return):
                        # `return tracer` marks the function name itself
                        # nothing: call-result taint is approximated by
                        # argument taint in _taint (Call case)
                        pass
                    elif isinstance(node, ast.Call):
                        self._propagate_call(node, scope)
            if self._changed() == before:
                break

def build_reachable(index: PackageIndex) -> List[FuncInfo]:
    """Fixpoint over the call graph: analyze every jit root, propagate
    parameter taints into in-package callees, repeat until stable.
    Returns the analyzed FuncInfos (roots + jit-reachable callees) with
    `tainted_params` filled in; walkers are cached on each FuncInfo as
    `_walker` for the rules to consume."""
    work: List[FuncInfo] = []
    for mi in index.modules.values():
        for fi in mi.top_funcs.values():
            if fi.jit_root:
                a = fi.node.args
                names = [p.arg for p in getattr(a, "posonlyargs", [])]
                names += [p.arg for p in a.args]
                names += [p.arg for p in a.kwonlyargs]
                fi.tainted_params = set(names) - fi.static_params
                work.append(fi)
    analyzed: Dict[int, FuncInfo] = {}
    for _ in range(20):  # cross-function fixpoint
        changed = False
        queue = list(work) + [fi for fi in analyzed.values()
                              if not fi.jit_root]
        seen: Set[int] = set()
        for fi in queue:
            if id(fi) in seen or fi.node is None:
                continue
            seen.add(id(fi))
            walker = TaintWalker(index, fi)
            walker.run_env_fixpoint()
            fi._walker = walker  # type: ignore[attr-defined]
            analyzed[id(fi)] = fi
            for _, (callee, taints) in walker.callee_taints.items():
                new = taints - callee.tainted_params
                if new or id(callee) not in analyzed:
                    callee.tainted_params |= new
                    if id(callee) not in analyzed:
                        analyzed[id(callee)] = callee
                    changed = True
        if not changed:
            break
    return list(analyzed.values())
