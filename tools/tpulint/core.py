"""tpulint core: findings, rule registry, suppressions, runner.

The suite is the compile-time guard for the invariants that determine
TPU performance (docs/StaticAnalysis.md): a stray host sync or a
weak-typed literal inside the jitted tree program costs a device round
trip or a recompile per iteration — regressions PR 2's recompile
watchdog can only catch at runtime, after the fact.  tpulint moves the
enforcement to lint time, the way the reference enforces its logging
and CHECK_* discipline at compile time (ref: include/LightGBM/utils/
log.h).

Design: every rule is a registered object with a `check(ctx)` returning
`Finding`s; the runner parses the package once into a `LintContext`
(ASTs + per-line suppressions) shared by all rules.  Suppressions are
per-line:

    x = float(s)  # tpulint: disable=no-host-sync-in-jit -- why it's ok
    # tpulint: disable-next=explicit-dtype -- why it's ok
    y = jnp.zeros(n)

A justification (the text after `--`) is REQUIRED: a disable comment
without one is itself reported (rule `bad-suppression`), so the merge
bar "every suppression carries a justification" is enforced
mechanically, not by review.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*disable(?P<next>-next)?\s*=\s*"
    r"(?P<rules>[\w,\-]+)"
    r"(?:\s*--\s*(?P<why>.*\S))?")


@dataclass
class Finding:
    """One lint finding; `suppressed` is filled in by the runner."""
    rule: str
    path: str          # relative to the linted package's parent
    line: int
    col: int
    message: str
    suppressed: bool = False
    justification: str = ""

    def to_dict(self) -> Dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "suppressed": self.suppressed,
                "justification": self.justification}

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] " \
               f"{self.message}{tag}"


@dataclass
class Suppression:
    rules: Set[str]
    justification: str
    line: int           # line the suppression APPLIES to
    comment_line: int   # line the comment sits on
    used: bool = False


@dataclass
class PyFile:
    """One parsed source file of the linted tree."""
    abspath: str
    rel: str            # relative to the package parent (e.g. lightgbm_tpu/engine.py)
    pkg_rel: str        # relative to the package dir (e.g. engine.py)
    source: str
    tree: Optional[ast.AST]
    parse_error: Optional[SyntaxError]
    # line -> suppressions applying to that line
    suppressions: Dict[int, List[Suppression]] = field(default_factory=dict)


def _parse_suppressions(source: str) -> List[Suppression]:
    out: List[Suppression] = []
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
        target = i + 1 if m.group("next") else i
        out.append(Suppression(rules=rules,
                               justification=(m.group("why") or "").strip(),
                               line=target, comment_line=i))
    return out


class LintContext:
    """Parsed view of one package tree, shared by all rules."""

    def __init__(self, package_dir: str, docs_dir: Optional[str] = None):
        self.package_dir = os.path.abspath(package_dir)
        self.root = os.path.dirname(self.package_dir)
        self.package_name = os.path.basename(self.package_dir)
        self.docs_dir = docs_dir or os.path.join(self.root, "docs")
        self.files: List[PyFile] = []
        self._load()

    def _load(self) -> None:
        for dirpath, dirnames, filenames in os.walk(self.package_dir):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                abspath = os.path.join(dirpath, fname)
                with open(abspath, encoding="utf-8") as f:
                    source = f.read()
                tree, err = None, None
                try:
                    tree = ast.parse(source, filename=abspath)
                except SyntaxError as e:
                    err = e
                pf = PyFile(
                    abspath=abspath,
                    rel=os.path.relpath(abspath, self.root),
                    pkg_rel=os.path.relpath(abspath, self.package_dir),
                    source=source, tree=tree, parse_error=err)
                for sup in _parse_suppressions(source):
                    pf.suppressions.setdefault(sup.line, []).append(sup)
                self.files.append(pf)

    def file_by_pkg_rel(self, pkg_rel: str) -> Optional[PyFile]:
        for pf in self.files:
            if pf.pkg_rel == pkg_rel:
                return pf
        return None


class Rule:
    """Base class: subclasses set `name`/`description` and implement
    check().  Adding a rule = subclass + @register (docs/StaticAnalysis.md
    "Adding a rule").

    Rules whose findings depend on ONE file at a time set
    `file_local = True` and implement `check_file(ctx, pf)`; the
    mtime-keyed cache then reuses their per-file results for unchanged
    files.  Graph rules (anything consuming the jit call graph) stay
    file_local = False and re-run whenever any file changed.

    Rules with `ir = True` (tools/tpulint/ir/) run over abstractly
    traced jaxprs of the package's manifest entries instead of ASTs;
    they are selected only by `--ir` (or by explicit name) and driven
    by the shared IR pass, never the per-file loop."""
    name: str = ""
    description: str = ""
    file_local: bool = False
    ir: bool = False

    def check(self, ctx: LintContext) -> List[Finding]:
        if not self.file_local:
            raise NotImplementedError
        out: List[Finding] = []
        for pf in ctx.files:
            out.extend(self.check_file(ctx, pf))
        return out

    def check_file(self, ctx: LintContext, pf: PyFile) -> List[Finding]:
        raise NotImplementedError


RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator adding a rule to the global registry."""
    inst = cls()
    assert inst.name and inst.name not in RULES, f"bad rule: {cls}"
    RULES[inst.name] = inst
    return cls


@dataclass
class Report:
    findings: List[Finding]

    @property
    def active(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    def to_dict(self) -> Dict:
        counts: Dict[str, int] = {}
        for f in self.active:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {"findings": [f.to_dict() for f in self.findings],
                "counts": counts,
                "num_active": len(self.active),
                "num_suppressed": len(self.suppressed)}

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def render_text(self) -> str:
        lines = [f.render() for f in self.findings]
        lines.append(f"{len(self.active)} finding(s), "
                     f"{len(self.suppressed)} suppressed")
        return "\n".join(lines)


def _apply_suppressions(ctx: LintContext, findings: List[Finding]
                        ) -> List[Finding]:
    by_rel = {pf.rel: pf for pf in ctx.files}
    for f in findings:
        pf = by_rel.get(f.path)
        if pf is None:
            continue
        for sup in pf.suppressions.get(f.line, []):
            if f.rule in sup.rules:
                f.suppressed = True
                f.justification = sup.justification
                sup.used = True
    # a suppression without a justification defeats the audit trail:
    # report it as a finding of its own (never suppressible)
    for pf in ctx.files:
        for sups in pf.suppressions.values():
            for sup in sups:
                if not sup.justification:
                    findings.append(Finding(
                        rule="bad-suppression", path=pf.rel,
                        line=sup.comment_line, col=0,
                        message="tpulint disable comment without a "
                                "justification (append ' -- <reason>')"))
    return findings


# ------------------------------------------------------------------ cache
# mtime-keyed analysis cache (docs/StaticAnalysis.md "Caching"): the
# full-package lint re-parses every file and rebuilds the jit call
# graph, which grows with the package.  The cache keys on every file's
# (mtime_ns, size) plus a CONTENT hash of tpulint's own sources: a
# fully-unchanged package returns the stored report without any
# analysis (sub-second); when only some files changed, file-local rules
# reuse their per-file results for the unchanged ones and graph rules
# re-run.  The tool side hashes content, not mtimes (ISSUE 12): a rule
# edit that preserves (mtime, size) — git checkout/stash restores,
# build-system copies, same-second editor saves — previously served
# STALE per-file results for the edited rule until --no-cache.

CACHE_VERSION = 2


def _tool_fingerprint(tool_dir: Optional[str] = None) -> List:
    import hashlib
    d = tool_dir or os.path.dirname(os.path.abspath(__file__))
    items: List = []
    for root, dirs, files in os.walk(d):
        dirs[:] = sorted(x for x in dirs if x != "__pycache__")
        for fname in sorted(files):
            if fname.endswith(".py"):
                p = os.path.join(root, fname)
                with open(p, "rb") as f:
                    digest = hashlib.sha256(f.read()).hexdigest()[:16]
                items.append([os.path.relpath(p, d), digest])
    return items


def _stat_key(path: str) -> Optional[List]:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return [int(st.st_mtime_ns), st.st_size]


def _load_cache(path: str) -> Optional[Dict]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if isinstance(data, dict):
            return data
    except (OSError, ValueError):
        pass
    return None


def _save_cache(path: str, data: Dict) -> None:
    try:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f)
        os.replace(tmp, path)
    except OSError:
        pass  # a cache problem must never fail the lint


def default_cache_path(package_dir: str) -> str:
    return os.path.join(os.path.dirname(os.path.abspath(package_dir)),
                        ".tpulint_cache.json")


# --------------------------------------------------------- parallel pass
# Cold-run fan-out (ISSUE 9): the per-file rule passes are independent,
# so they spread over a fork()-based process pool — each child inherits
# the parsed LintContext (and the already-built call graph) copy-on-
# write, runs every file-local rule for its files, and ships back plain
# finding dicts.  The call-graph pass itself stays single-process by
# design (its fixpoint is global), and the warm mtime-cache path is
# untouched.  Engaged only when it can win: fork available, >1 CPU, and
# enough uncached files to amortize the pool spin-up.

_PARALLEL_STATE: Optional[Tuple] = None  # (ctx, set at fork time)


def _file_local_child(args) -> List[Tuple[str, str, List[Dict]]]:
    rel, rule_names = args
    ctx = _PARALLEL_STATE
    pf = next(p for p in ctx.files if p.rel == rel)
    out = []
    for name in rule_names:
        fs = RULES[name].check_file(ctx, pf)
        out.append((rel, name, [f.to_dict() for f in fs]))
    return out


def _run_file_local(ctx, pending: List[Tuple[str, List[str]]],
                    jobs: Optional[int]
                    ) -> List[Tuple[str, str, List[Dict]]]:
    """(rel, rule, finding-dicts) for every pending (file, rules) unit,
    serially or across a fork pool."""
    import multiprocessing

    eff = jobs if jobs is not None else (os.cpu_count() or 1)
    eff = min(eff, len(pending))
    use_pool = eff > 1 and len(pending) >= 8 \
        and "fork" in multiprocessing.get_all_start_methods()
    if use_pool:
        global _PARALLEL_STATE
        _PARALLEL_STATE = ctx
        try:
            with multiprocessing.get_context("fork").Pool(eff) as pool:
                chunks = pool.map(_file_local_child, pending,
                                  chunksize=max(1, len(pending) // eff))
            return [item for chunk in chunks for item in chunk]
        except Exception:
            pass  # a pool problem must never fail the lint: fall through
        finally:
            _PARALLEL_STATE = None
    by_rel = {pf.rel: pf for pf in ctx.files}
    out = []
    for rel, rule_names in pending:
        pf = by_rel[rel]
        for name in rule_names:
            fs = RULES[name].check_file(ctx, pf)
            out.append((rel, name, [f.to_dict() for f in fs]))
    return out


def _package_source_hash(ctx: LintContext) -> str:
    """Content hash of every source file of the linted tree — the
    conservative key for the IR result cache (an edit anywhere can
    change a traced program through imports)."""
    import hashlib
    h = hashlib.sha256()
    for pf in sorted(ctx.files, key=lambda p: p.rel):
        h.update(pf.rel.encode())
        h.update(b"\0")
        h.update(pf.source.encode())
        h.update(b"\0")
    return h.hexdigest()[:16]


def _ir_findings_and_section(ctx: LintContext, ir_selected: List[str],
                             cache: Optional[Dict], key: Dict
                             ) -> Tuple[List[Finding], Dict]:
    """The IR pass behind its own cache section: results are stored
    per run keyed on (package content hash, tool content hash, rule
    set), with each traced entry's exemplar-signature hash recorded
    (docs/StaticAnalysis.md v4 "Caching") — a key hit replays the
    findings without importing jax or tracing anything."""
    cached = (cache or {}).get("ir")
    if cached is not None and cached.get("key") == key:
        fs = [Finding(**d) for d in cached.get("findings", [])]
        for f in fs:
            f.suppressed, f.justification = False, ""
        return fs, cached
    from .ir.rules import run_ir_pass
    fs, _n, sigs = run_ir_pass(ctx, rule_names=list(ir_selected))
    section = {"key": key, "entry_sigs": sigs,
               "findings": [dict(f.to_dict(), suppressed=False,
                                 justification="") for f in fs]}
    return fs, section


def run_lint(package_dir: str, rules: Optional[List[str]] = None,
             docs_dir: Optional[str] = None,
             cache_path: Optional[str] = None,
             jobs: Optional[int] = None, ir: bool = False) -> Report:
    """Run the (selected) rules over one package tree.  With
    `cache_path`, reuse mtime-keyed results (see module comment); with
    `jobs` != 1, fan the per-file rule passes out across a process pool
    (None = one worker per CPU).  `ir=True` additionally runs the
    jaxpr-level rules over the package's `_lint_entries.py` manifest
    (tools/tpulint/ir/); ir rules also run when named in `rules`."""
    # rule modules self-register on import
    from . import rules as _rules  # noqa: F401
    ctx = LintContext(package_dir, docs_dir=docs_dir)
    if rules is None:
        selected = [n for n in RULES if not RULES[n].ir]
        ir_selected = sorted(n for n in RULES if RULES[n].ir) if ir \
            else []
    else:
        for name in rules:
            if name not in RULES:
                raise KeyError(f"unknown tpulint rule: {name} "
                               f"(known: {', '.join(sorted(RULES))})")
        selected = [n for n in rules if not RULES[n].ir]
        ir_selected = [n for n in rules if RULES[n].ir]
        if ir and not ir_selected:
            ir_selected = sorted(n for n in RULES if RULES[n].ir)

    fkeys = {pf.rel: _stat_key(pf.abspath) for pf in ctx.files}
    meta = {"version": CACHE_VERSION, "tool": _tool_fingerprint(),
            "rules": sorted(selected),
            "docs": _stat_key(os.path.join(ctx.docs_dir,
                                           "Parameters.md"))}
    cache = _load_cache(cache_path) if cache_path else None
    if cache is not None and cache.get("meta") != meta:
        cache = None  # tool or rule set changed: full invalidation
    ir_key = ({"pkg": _package_source_hash(ctx), "tool": meta["tool"],
               "rules": sorted(ir_selected)} if ir_selected else None)
    if cache is not None and cache.get("files") == fkeys:
        if not ir_selected:
            return Report(findings=[Finding(**d)
                                    for d in cache.get("findings", [])])
        # AST results replay from cache; the IR section replays or
        # re-traces on its own key, then suppressions re-apply to the
        # merged list (bad-suppression findings regenerate there)
        ast_findings = [Finding(**d) for d in cache.get("findings", [])
                        if d.get("rule") != "bad-suppression"]
        for f in ast_findings:
            f.suppressed, f.justification = False, ""
        ir_findings, ir_section = _ir_findings_and_section(
            ctx, ir_selected, cache, ir_key)
        merged = _apply_suppressions(ctx, ast_findings + ir_findings)
        merged.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        if cache_path:
            _save_cache(cache_path, dict(cache, ir=ir_section))
        return Report(findings=merged)

    findings: List[Finding] = []
    for pf in ctx.files:
        if pf.parse_error is not None:
            findings.append(Finding(
                rule="syntax-error", path=pf.rel,
                line=pf.parse_error.lineno or 0, col=0,
                message=f"cannot parse: {pf.parse_error.msg}"))
    cached_files = (cache or {}).get("files", {})
    cached_per_file = (cache or {}).get("per_file", {})
    per_file: Dict[str, Dict[str, List[Dict]]] = {}
    file_local = [n for n in selected if RULES[n].file_local]
    # graph rules first: they build the shared index/reachable set the
    # forked children then inherit copy-on-write
    for name in selected:
        if not RULES[name].file_local:
            findings.extend(RULES[name].check(ctx))
    pending: List[Tuple[str, List[str]]] = []
    for pf in ctx.files:
        unchanged = (cached_files.get(pf.rel) == fkeys[pf.rel])
        need: List[str] = []
        for name in file_local:
            cached_l = (cached_per_file.get(pf.rel, {}).get(name)
                        if unchanged else None)
            if cached_l is not None:
                fs = [Finding(**d) for d in cached_l]
                for f in fs:
                    f.suppressed, f.justification = False, ""
                per_file.setdefault(pf.rel, {})[name] = [
                    dict(f.to_dict(), suppressed=False, justification="")
                    for f in fs]
                findings.extend(fs)
            else:
                need.append(name)
        if need:
            pending.append((pf.rel, need))
    for rel, name, dicts in _run_file_local(ctx, pending, jobs):
        fs = [Finding(**d) for d in dicts]
        per_file.setdefault(rel, {})[name] = [
            dict(d, suppressed=False, justification="") for d in dicts]
        findings.extend(fs)
    ir_section = (cache or {}).get("ir")
    if ir_selected:
        ir_findings, ir_section = _ir_findings_and_section(
            ctx, ir_selected, cache, ir_key)
        findings.extend(ir_findings)
    findings = _apply_suppressions(ctx, findings)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    report = Report(findings=findings)
    if cache_path:
        # the stored findings stay AST-only: a later non-ir run's
        # short-circuit must not replay IR findings it did not select
        ast_only = [f.to_dict() for f in report.findings
                    if not getattr(RULES.get(f.rule), "ir", False)]
        payload = {"meta": meta, "files": fkeys, "findings": ast_only,
                   "per_file": per_file}
        if ir_section is not None:
            payload["ir"] = ir_section
        _save_cache(cache_path, payload)
    return report


# --------------------------------------------------------------- baseline
def baseline_counts(report: Report) -> Dict[str, int]:
    """Per-(rule, file) counts of the ACTIVE findings — the baseline
    format.  Line- and message-insensitive so ordinary edits do not
    churn it; only fixing or introducing findings moves the counts."""
    counts: Dict[str, int] = {}
    for f in report.active:
        key = f"{f.rule}|{f.path}"
        counts[key] = counts.get(key, 0) + 1
    return counts


def write_baseline(path: str, report: Report) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"tpulint_baseline": 1,
                   "counts": baseline_counts(report)}, f, indent=2,
                  sort_keys=True)
        f.write("\n")


def apply_baseline(report: Report, path: str) -> Tuple[List[Finding], int]:
    """Split the active findings into (new, num_accepted): up to the
    baseline's per-(rule, file) count of legacy findings is accepted
    (earliest lines first); anything beyond it is NEW and fails CI."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    budget = dict(data.get("counts", {}))
    new: List[Finding] = []
    accepted = 0
    for f in sorted(report.active, key=lambda x: (x.path, x.line, x.col)):
        key = f"{f.rule}|{f.path}"
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            accepted += 1
        else:
            new.append(f)
    return new, accepted


# ---------------------------------------------------------------- SARIF
def to_sarif(report: Report, failing: Optional[List[Finding]] = None
             ) -> Dict:
    """SARIF 2.1.0 for `--format=sarif`: the standard interchange format
    PR annotation tooling (GitHub code scanning, reviewdog, IDEs)
    ingests directly.  `failing` narrows the results to the
    post-baseline NEW findings, mirroring the github format's
    semantics; default is every active finding."""
    results = report.active if failing is None else failing
    rule_ids = sorted({f.rule for f in results} | set(RULES))
    rules_meta = []
    for rid in rule_ids:
        entry = {"id": rid}
        rule = RULES.get(rid)
        if rule is not None:
            entry["shortDescription"] = {"text": rule.description}
        rules_meta.append(entry)
    index_of = {rid: i for i, rid in enumerate(rule_ids)}
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tpulint",
                "informationUri": "docs/StaticAnalysis.md",
                "rules": rules_meta,
            }},
            "results": [{
                "ruleId": f.rule,
                "ruleIndex": index_of[f.rule],
                "level": "error",
                "message": {"text": f.message},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path.replace(os.sep, "/")},
                        "region": {"startLine": max(f.line, 1),
                                   "startColumn": f.col + 1},
                    }}],
            } for f in results],
        }],
    }


# ----------------------------------------------------------- suppressions
def iter_suppressions(package_dir: str):
    """Yield (rel_path, comment_line, rules, justification) for every
    tpulint disable comment in the package — the audit listing behind
    `--list-suppressions`."""
    ctx = LintContext(package_dir)
    for pf in ctx.files:
        for sups in pf.suppressions.values():
            for sup in sups:
                yield (pf.rel, sup.comment_line, sorted(sup.rules),
                       sup.justification)


def audit_suppressions(package_dir: str,
                       cache_path: Optional[str] = None,
                       ir: bool = False):
    """`iter_suppressions` plus a liveness verdict: the full rule suite
    runs and each suppression is matched against the findings it
    actually masked.  A suppression masking NOTHING is stale — its
    finding was resolved (the way `wave.py:_psum` resolved when the v2
    graph closed the shard_map distance) and keeping the comment would
    silently swallow a future regression at that line.  With `ir`, the
    jaxpr-level rules run too, so a manifest-line ir suppression
    registers as live.  Yields
    (rel_path, comment_line, rules, justification, used)."""
    report = run_lint(package_dir, cache_path=cache_path, ir=ir)
    masked = {(f.path, f.line, f.rule) for f in report.suppressed}
    ctx = LintContext(package_dir)
    for pf in ctx.files:
        for sups in pf.suppressions.values():
            for sup in sups:
                used = any((pf.rel, sup.line, r) in masked
                           for r in sup.rules)
                yield (pf.rel, sup.comment_line, sorted(sup.rules),
                       sup.justification, used)
