"""tpulint core: findings, rule registry, suppressions, runner.

The suite is the compile-time guard for the invariants that determine
TPU performance (docs/StaticAnalysis.md): a stray host sync or a
weak-typed literal inside the jitted tree program costs a device round
trip or a recompile per iteration — regressions PR 2's recompile
watchdog can only catch at runtime, after the fact.  tpulint moves the
enforcement to lint time, the way the reference enforces its logging
and CHECK_* discipline at compile time (ref: include/LightGBM/utils/
log.h).

Design: every rule is a registered object with a `check(ctx)` returning
`Finding`s; the runner parses the package once into a `LintContext`
(ASTs + per-line suppressions) shared by all rules.  Suppressions are
per-line:

    x = float(s)  # tpulint: disable=no-host-sync-in-jit -- why it's ok
    # tpulint: disable-next=explicit-dtype -- why it's ok
    y = jnp.zeros(n)

A justification (the text after `--`) is REQUIRED: a disable comment
without one is itself reported (rule `bad-suppression`), so the merge
bar "every suppression carries a justification" is enforced
mechanically, not by review.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*disable(?P<next>-next)?\s*=\s*"
    r"(?P<rules>[\w,\-]+)"
    r"(?:\s*--\s*(?P<why>.*\S))?")


@dataclass
class Finding:
    """One lint finding; `suppressed` is filled in by the runner."""
    rule: str
    path: str          # relative to the linted package's parent
    line: int
    col: int
    message: str
    suppressed: bool = False
    justification: str = ""

    def to_dict(self) -> Dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "suppressed": self.suppressed,
                "justification": self.justification}

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] " \
               f"{self.message}{tag}"


@dataclass
class Suppression:
    rules: Set[str]
    justification: str
    line: int           # line the suppression APPLIES to
    comment_line: int   # line the comment sits on
    used: bool = False


@dataclass
class PyFile:
    """One parsed source file of the linted tree."""
    abspath: str
    rel: str            # relative to the package parent (e.g. lightgbm_tpu/engine.py)
    pkg_rel: str        # relative to the package dir (e.g. engine.py)
    source: str
    tree: Optional[ast.AST]
    parse_error: Optional[SyntaxError]
    # line -> suppressions applying to that line
    suppressions: Dict[int, List[Suppression]] = field(default_factory=dict)


def _parse_suppressions(source: str) -> List[Suppression]:
    out: List[Suppression] = []
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
        target = i + 1 if m.group("next") else i
        out.append(Suppression(rules=rules,
                               justification=(m.group("why") or "").strip(),
                               line=target, comment_line=i))
    return out


class LintContext:
    """Parsed view of one package tree, shared by all rules."""

    def __init__(self, package_dir: str, docs_dir: Optional[str] = None):
        self.package_dir = os.path.abspath(package_dir)
        self.root = os.path.dirname(self.package_dir)
        self.package_name = os.path.basename(self.package_dir)
        self.docs_dir = docs_dir or os.path.join(self.root, "docs")
        self.files: List[PyFile] = []
        self._load()

    def _load(self) -> None:
        for dirpath, dirnames, filenames in os.walk(self.package_dir):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                abspath = os.path.join(dirpath, fname)
                with open(abspath, encoding="utf-8") as f:
                    source = f.read()
                tree, err = None, None
                try:
                    tree = ast.parse(source, filename=abspath)
                except SyntaxError as e:
                    err = e
                pf = PyFile(
                    abspath=abspath,
                    rel=os.path.relpath(abspath, self.root),
                    pkg_rel=os.path.relpath(abspath, self.package_dir),
                    source=source, tree=tree, parse_error=err)
                for sup in _parse_suppressions(source):
                    pf.suppressions.setdefault(sup.line, []).append(sup)
                self.files.append(pf)

    def file_by_pkg_rel(self, pkg_rel: str) -> Optional[PyFile]:
        for pf in self.files:
            if pf.pkg_rel == pkg_rel:
                return pf
        return None


class Rule:
    """Base class: subclasses set `name`/`description` and implement
    check().  Adding a rule = subclass + @register (docs/StaticAnalysis.md
    "Adding a rule").

    Rules whose findings depend on ONE file at a time set
    `file_local = True` and implement `check_file(ctx, pf)`; the
    mtime-keyed cache then reuses their per-file results for unchanged
    files.  Graph rules (anything consuming the jit call graph) stay
    file_local = False and re-run whenever any file changed."""
    name: str = ""
    description: str = ""
    file_local: bool = False

    def check(self, ctx: LintContext) -> List[Finding]:
        if not self.file_local:
            raise NotImplementedError
        out: List[Finding] = []
        for pf in ctx.files:
            out.extend(self.check_file(ctx, pf))
        return out

    def check_file(self, ctx: LintContext, pf: PyFile) -> List[Finding]:
        raise NotImplementedError


RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator adding a rule to the global registry."""
    inst = cls()
    assert inst.name and inst.name not in RULES, f"bad rule: {cls}"
    RULES[inst.name] = inst
    return cls


@dataclass
class Report:
    findings: List[Finding]

    @property
    def active(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    def to_dict(self) -> Dict:
        counts: Dict[str, int] = {}
        for f in self.active:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {"findings": [f.to_dict() for f in self.findings],
                "counts": counts,
                "num_active": len(self.active),
                "num_suppressed": len(self.suppressed)}

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def render_text(self) -> str:
        lines = [f.render() for f in self.findings]
        lines.append(f"{len(self.active)} finding(s), "
                     f"{len(self.suppressed)} suppressed")
        return "\n".join(lines)


def _apply_suppressions(ctx: LintContext, findings: List[Finding]
                        ) -> List[Finding]:
    by_rel = {pf.rel: pf for pf in ctx.files}
    for f in findings:
        pf = by_rel.get(f.path)
        if pf is None:
            continue
        for sup in pf.suppressions.get(f.line, []):
            if f.rule in sup.rules:
                f.suppressed = True
                f.justification = sup.justification
                sup.used = True
    # a suppression without a justification defeats the audit trail:
    # report it as a finding of its own (never suppressible)
    for pf in ctx.files:
        for sups in pf.suppressions.values():
            for sup in sups:
                if not sup.justification:
                    findings.append(Finding(
                        rule="bad-suppression", path=pf.rel,
                        line=sup.comment_line, col=0,
                        message="tpulint disable comment without a "
                                "justification (append ' -- <reason>')"))
    return findings


# ------------------------------------------------------------------ cache
# mtime-keyed analysis cache (docs/StaticAnalysis.md "Caching"): the
# full-package lint re-parses every file and rebuilds the jit call
# graph, which grows with the package.  The cache keys on every file's
# (mtime_ns, size) plus tpulint's own sources: a fully-unchanged
# package returns the stored report without any analysis (sub-second);
# when only some files changed, file-local rules reuse their per-file
# results for the unchanged ones and graph rules re-run.

CACHE_VERSION = 1


def _tool_fingerprint() -> List:
    d = os.path.dirname(os.path.abspath(__file__))
    items: List = []
    for root, dirs, files in os.walk(d):
        dirs[:] = sorted(x for x in dirs if x != "__pycache__")
        for fname in sorted(files):
            if fname.endswith(".py"):
                p = os.path.join(root, fname)
                st = os.stat(p)
                items.append([os.path.relpath(p, d),
                              int(st.st_mtime_ns), st.st_size])
    return items


def _stat_key(path: str) -> Optional[List]:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return [int(st.st_mtime_ns), st.st_size]


def _load_cache(path: str) -> Optional[Dict]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if isinstance(data, dict):
            return data
    except (OSError, ValueError):
        pass
    return None


def _save_cache(path: str, data: Dict) -> None:
    try:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f)
        os.replace(tmp, path)
    except OSError:
        pass  # a cache problem must never fail the lint


def default_cache_path(package_dir: str) -> str:
    return os.path.join(os.path.dirname(os.path.abspath(package_dir)),
                        ".tpulint_cache.json")


def run_lint(package_dir: str, rules: Optional[List[str]] = None,
             docs_dir: Optional[str] = None,
             cache_path: Optional[str] = None) -> Report:
    """Run the (selected) rules over one package tree.  With
    `cache_path`, reuse mtime-keyed results (see module comment)."""
    # rule modules self-register on import
    from . import rules as _rules  # noqa: F401
    ctx = LintContext(package_dir, docs_dir=docs_dir)
    selected = list(RULES) if rules is None else list(rules)
    for name in selected:
        if name not in RULES:
            raise KeyError(f"unknown tpulint rule: {name} "
                           f"(known: {', '.join(sorted(RULES))})")

    fkeys = {pf.rel: _stat_key(pf.abspath) for pf in ctx.files}
    meta = {"version": CACHE_VERSION, "tool": _tool_fingerprint(),
            "rules": sorted(selected),
            "docs": _stat_key(os.path.join(ctx.docs_dir,
                                           "Parameters.md"))}
    cache = _load_cache(cache_path) if cache_path else None
    if cache is not None and cache.get("meta") != meta:
        cache = None  # tool or rule set changed: full invalidation
    if cache is not None and cache.get("files") == fkeys:
        return Report(findings=[Finding(**d)
                                for d in cache.get("findings", [])])

    findings: List[Finding] = []
    for pf in ctx.files:
        if pf.parse_error is not None:
            findings.append(Finding(
                rule="syntax-error", path=pf.rel,
                line=pf.parse_error.lineno or 0, col=0,
                message=f"cannot parse: {pf.parse_error.msg}"))
    cached_files = (cache or {}).get("files", {})
    cached_per_file = (cache or {}).get("per_file", {})
    per_file: Dict[str, Dict[str, List[Dict]]] = {}
    for name in selected:
        rule = RULES[name]
        if not rule.file_local:
            findings.extend(rule.check(ctx))
            continue
        for pf in ctx.files:
            unchanged = (cached_files.get(pf.rel) == fkeys[pf.rel])
            cached_l = (cached_per_file.get(pf.rel, {}).get(name)
                        if unchanged else None)
            if cached_l is not None:
                fs = [Finding(**d) for d in cached_l]
                for f in fs:
                    f.suppressed, f.justification = False, ""
            else:
                fs = rule.check_file(ctx, pf)
            per_file.setdefault(pf.rel, {})[name] = [
                dict(f.to_dict(), suppressed=False, justification="")
                for f in fs]
            findings.extend(fs)
    findings = _apply_suppressions(ctx, findings)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    report = Report(findings=findings)
    if cache_path:
        _save_cache(cache_path, {
            "meta": meta, "files": fkeys,
            "findings": [f.to_dict() for f in report.findings],
            "per_file": per_file})
    return report


# --------------------------------------------------------------- baseline
def baseline_counts(report: Report) -> Dict[str, int]:
    """Per-(rule, file) counts of the ACTIVE findings — the baseline
    format.  Line- and message-insensitive so ordinary edits do not
    churn it; only fixing or introducing findings moves the counts."""
    counts: Dict[str, int] = {}
    for f in report.active:
        key = f"{f.rule}|{f.path}"
        counts[key] = counts.get(key, 0) + 1
    return counts


def write_baseline(path: str, report: Report) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"tpulint_baseline": 1,
                   "counts": baseline_counts(report)}, f, indent=2,
                  sort_keys=True)
        f.write("\n")


def apply_baseline(report: Report, path: str) -> Tuple[List[Finding], int]:
    """Split the active findings into (new, num_accepted): up to the
    baseline's per-(rule, file) count of legacy findings is accepted
    (earliest lines first); anything beyond it is NEW and fails CI."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    budget = dict(data.get("counts", {}))
    new: List[Finding] = []
    accepted = 0
    for f in sorted(report.active, key=lambda x: (x.path, x.line, x.col)):
        key = f"{f.rule}|{f.path}"
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            accepted += 1
        else:
            new.append(f)
    return new, accepted


# ----------------------------------------------------------- suppressions
def iter_suppressions(package_dir: str):
    """Yield (rel_path, comment_line, rules, justification) for every
    tpulint disable comment in the package — the audit listing behind
    `--list-suppressions`."""
    ctx = LintContext(package_dir)
    for pf in ctx.files:
        for sups in pf.suppressions.values():
            for sup in sups:
                yield (pf.rel, sup.comment_line, sorted(sup.rules),
                       sup.justification)
