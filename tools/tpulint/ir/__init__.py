"""tpulint IR layer: jaxpr-level audit of the hot jitted entries.

The AST rules (tools/tpulint/rules/) see the SOURCE; this layer sees
the artifact that actually runs on the chip.  Every hot entry declared
in the linted package's `_lint_entries.py` manifest is abstractly
traced (jax .trace on exemplar ShapeDtypeStructs — no device, no data,
no compile) to its ClosedJaxpr, and the `ir-*` rule passes walk the
equations: float64 leaks, host callbacks, convert round trips, baked-in
giant constants and undeclared histogram shapes all live at this level
and are invisible to any AST rule.  Findings anchor at the manifest
entry's declaration line, so the ordinary per-line suppression syntax
(and the baseline/SARIF machinery) applies unchanged.

Entry via `python -m tools.tpulint --ir` (core.run_lint(ir=True)), or
programmatically through `run_ir_audit` (bench.py's `ir_audit_clean`).
"""

from .trace import load_manifest, trace_entry  # noqa: F401
from . import rules as _rules  # noqa: F401  (registers the ir-* rules)


def run_ir_audit(package_dir: str, groups=None):
    """Standalone IR audit for tooling (bench.py): trace the manifest
    entries of `package_dir` (optionally restricted to detector
    `groups`) and run every ir rule.  Returns (findings, num_traced) —
    `findings` already has per-line suppressions applied."""
    from ..core import LintContext, _apply_suppressions
    from .rules import run_ir_pass
    ctx = LintContext(package_dir)
    findings, num_traced, _sigs = run_ir_pass(ctx, rule_names=None,
                                              groups=groups)
    findings = _apply_suppressions(ctx, findings)
    # _apply_suppressions may append bad-suppression findings for the
    # whole package; an audit scoped to the manifest keeps only its own
    findings = [f for f in findings if f.rule.startswith("ir-")]
    return findings, num_traced
