"""The ir-* rule passes over abstractly traced ClosedJaxprs.

Each rule implements `check_entry(ctx, rel, entry, closed)` — one
traced manifest entry at a time — and anchors its findings at the
entry's declaration line in `<package>/_lint_entries.py`, so the
ordinary `# tpulint: disable=<rule> -- why` suppression syntax applies.
Pattern-level exemptions (a deliberate one-hot-dot histogram, a
deliberate sub-32-bit accumulator) are declared ON the entry instead
(`declares`), keeping the justification next to the entry it covers.

Rules (docs/StaticAnalysis.md v4):

* ir-no-f64          — float64 introduced anywhere in device code
* ir-no-callback     — host callbacks / transfers inside a hot entry
* ir-convert-churn   — convert_element_type round trips
* ir-giant-constant  — large literals baked into the program
* ir-scatter-audit   — histogram-path scatter/gather/one-hot shapes
* ir-manifest-coverage — every RecompileDetector entry has a manifest row
* ir-trace-error     — manifest/builder/trace failures (never silent)
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..core import Finding, LintContext, Rule, register
from .trace import (aval_of, dtype_name, iter_eqns, iter_jaxprs,
                    load_manifest, manifest_rel, trace_entry)

# consts at or above this size are "giant": they re-upload with every
# recompile, bloat the serialized executable, and defeat donation
# (256 KiB ~ a [64k] f32 buffer; real model/feature data must be an
# ARGUMENT, which is also what keeps the trace shape-generic)
GIANT_CONST_BYTES = 256 * 1024

# primitives that re-enter the host from device code: each one is a
# synchronization point that de-pipelines dispatch (and is outright
# unsupported inside a donated serving program)
CALLBACK_PRIMS = {
    "pure_callback", "io_callback", "debug_callback", "debug_print",
    "outside_call", "host_callback_call", "infeed", "outfeed",
}

NARROW_ACC_DTYPES = {"int8", "int16", "uint8", "uint16", "float16",
                     "bfloat16"}


class IRRule(Rule):
    """Base for jaxpr-level rules: selected only by `--ir` (or by
    name), driven by run_ir_pass — never by the per-file AST loop."""
    ir = True

    def check(self, ctx: LintContext) -> List[Finding]:
        # the shared trace/dispatch lives in run_ir_pass; a direct
        # check() call (legacy path) just runs the full pass filtered
        # to this rule
        findings, _n, _sigs = run_ir_pass(ctx, rule_names=[self.name])
        return findings

    def check_entry(self, ctx: LintContext, rel: str, entry,
                    closed) -> List[Finding]:
        raise NotImplementedError


def _f(rule: str, rel: str, entry, message: str) -> Finding:
    return Finding(rule=rule, path=rel, line=entry.line, col=0,
                   message=f"[{entry.name}] {message}")


@register
class NoF64(IRRule):
    name = "ir-no-f64"
    description = ("no float64 primitive, convert or constant in a hot "
                   "entry's jaxpr (weak-type f64 promotion is a latent "
                   "10-20x TPU slowdown invisible in source)")

    def check_entry(self, ctx, rel, entry, closed):
        out: List[Finding] = []
        flagged = set()
        for c in closed.consts:
            dt = str(getattr(c, "dtype", ""))
            if dt == "float64" and "const" not in flagged:
                flagged.add("const")
                shape = tuple(getattr(c, "shape", ()))
                out.append(_f(self.name, rel, entry,
                              f"float64 constant {shape} baked into the "
                              "program (a host-side numpy float64 "
                              "literal/array captured by the trace; "
                              "give it an explicit float32 dtype)"))
        for eq in iter_eqns(closed):
            in_f64 = any(dtype_name(v) == "float64" for v in eq.invars)
            intro = [v for v in eq.outvars
                     if dtype_name(v) == "float64"] if not in_f64 else []
            if intro and eq.primitive.name not in flagged:
                flagged.add(eq.primitive.name)
                out.append(_f(self.name, rel, entry,
                              f"primitive '{eq.primitive.name}' "
                              "introduces float64 into device code "
                              "(weak-type promotion from a float64 "
                              "host value; under x64 the whole "
                              "downstream program double-widths)"))
        return out


@register
class NoCallback(IRRule):
    name = "ir-no-callback"
    description = ("no host callback / host transfer primitive inside "
                   "a hot jitted entry (each is a device->host sync "
                   "that de-pipelines dispatch)")

    def check_entry(self, ctx, rel, entry, closed):
        out: List[Finding] = []
        flagged = set()
        for eq in iter_eqns(closed):
            p = eq.primitive.name
            if p in CALLBACK_PRIMS and p not in flagged:
                flagged.add(p)
                detail = ""
                cb = eq.params.get("callback")
                if cb is not None:
                    detail = f" ({cb!r})"
                out.append(_f(self.name, rel, entry,
                              f"host callback primitive '{p}'{detail} "
                              "inside the hot entry — every dispatch "
                              "round-trips the host; move it outside "
                              "the jitted program"))
        return out


def _kind(dt: str) -> str:
    if dt.startswith("float") or dt.startswith("bfloat"):
        return "f"
    if dt.startswith("int") or dt.startswith("uint"):
        return "i"
    return dt


def _itemsize(dt: str) -> int:
    import numpy as np
    try:
        return np.dtype(dt).itemsize
    except TypeError:
        return 2 if dt == "bfloat16" else 4


@register
class ConvertChurn(IRRule):
    name = "ir-convert-churn"
    description = ("no convert_element_type round trip (A->B->A with "
                   "no intervening compute, B at least as wide as A) — "
                   "pure HBM traffic; the guard rail for the "
                   "quantized-gradient work")

    def check_entry(self, ctx, rel, entry, closed):
        out: List[Finding] = []
        flagged = set()
        for j in iter_jaxprs(closed):
            producer: Dict[int, object] = {}
            for eq in j.eqns:
                for v in eq.outvars:
                    producer[id(v)] = eq
            for eq in j.eqns:
                if eq.primitive.name != "convert_element_type":
                    continue
                src = eq.invars[0]
                prev = producer.get(id(src))
                if prev is None or \
                        prev.primitive.name != "convert_element_type":
                    continue
                a = dtype_name(prev.invars[0])
                b = dtype_name(src)
                c = dtype_name(eq.outvars[0])
                if a is None or b is None or c != a:
                    continue
                # A->B->A through a NARROWER B is a deliberate
                # precision squeeze (bf16/int8 quantization); through a
                # same-or-wider same-kind B it is pure churn.  A kind
                # change (f->i) is value-truncating, i.e. semantic.
                if _kind(a) == _kind(b) and \
                        _itemsize(b) >= _itemsize(a):
                    key = (a, b)
                    if key not in flagged:
                        flagged.add(key)
                        out.append(_f(
                            self.name, rel, entry,
                            f"convert round trip {a} -> {b} -> {a} "
                            "with no intervening compute — two "
                            "full-array HBM passes for nothing"))
        return out


@register
class GiantConstant(IRRule):
    name = "ir-giant-constant"
    description = (f"no constant >= {GIANT_CONST_BYTES // 1024} KiB "
                   "baked into a hot entry's jaxpr (re-uploaded per "
                   "recompile, bloats the executable; pass it as an "
                   "argument)")

    def check_entry(self, ctx, rel, entry, closed):
        out: List[Finding] = []
        for c in closed.consts:
            nbytes = getattr(c, "nbytes", 0)
            if nbytes >= GIANT_CONST_BYTES:
                shape = tuple(getattr(c, "shape", ()))
                dt = getattr(c, "dtype", "?")
                out.append(_f(
                    self.name, rel, entry,
                    f"{nbytes / 1024:.0f} KiB constant {shape} {dt} "
                    "baked into the program — closed-over device data "
                    "recompiles into every executable and occupies "
                    "HBM per trace; pass it as an explicit argument"))
        return out


def _onehot_operand(j, eq) -> bool:
    """True when one operand of a dot_general derives (through
    convert/broadcast/transpose/reshape) from eq(iota, x) — the XLA
    one-hot histogram trick."""
    producer = {}
    for e in j.eqns:
        for v in e.outvars:
            producer[id(v)] = e
    PASS = {"convert_element_type", "broadcast_in_dim", "transpose",
            "reshape", "squeeze"}
    for opnd in eq.invars[:2]:
        e, hops = producer.get(id(opnd)), 0
        while e is not None and e.primitive.name in PASS and hops < 4:
            e = producer.get(id(e.invars[0]))
            hops += 1
        if e is not None and e.primitive.name == "eq":
            for v in e.invars:
                pe = producer.get(id(v))
                while pe is not None and pe.primitive.name in PASS:
                    pe = producer.get(id(pe.invars[0]))
                if pe is not None and pe.primitive.name == "iota":
                    return True
    return False


@register
class ScatterAudit(IRRule):
    name = "ir-scatter-audit"
    description = ("histogram-path shape audit: one-hot x dot "
                   "histograms and sub-32-bit scatter accumulators "
                   "must be DECLARED on their manifest entry "
                   "('onehot-dot' / 'narrow-acc')")

    def check_entry(self, ctx, rel, entry, closed):
        out: List[Finding] = []
        declares = getattr(entry, "declares", frozenset())
        saw_onehot = saw_narrow = False
        for j in iter_jaxprs(closed):
            for eq in j.eqns:
                p = eq.primitive.name
                if p == "dot_general" and not saw_onehot \
                        and "onehot-dot" not in declares \
                        and _onehot_operand(j, eq):
                    saw_onehot = True
                    out.append(_f(
                        self.name, rel, entry,
                        "undeclared one-hot x dot histogram shape "
                        "(materializes the [n, bins] one-hot in HBM; "
                        "the Pallas histogram kernel replaces it — "
                        "declare 'onehot-dot' on the entry if this "
                        "engine variant is meant to use it)"))
                if p in ("scatter-add", "scatter_add") and not saw_narrow \
                        and "narrow-acc" not in declares:
                    acc = dtype_name(eq.invars[0]) or ""
                    if acc in NARROW_ACC_DTYPES:
                        saw_narrow = True
                        out.append(_f(
                            self.name, rel, entry,
                            f"undeclared {acc} scatter accumulator — "
                            "sub-32-bit histogram entries overflow "
                            "silently; declare 'narrow-acc' if this is "
                            "the deliberate quantized path"))
        return out


@register
class TraceError(IRRule):
    name = "ir-trace-error"
    description = ("the IR audit could trace every manifest entry "
                   "(reports manifest import / builder / trace "
                   "failures — a hot entry the audit cannot see is "
                   "itself a finding)")

    def check_entry(self, ctx, rel, entry, closed):
        return []  # emitted by run_ir_pass, not per traced entry


@register
class ManifestCoverage(IRRule):
    name = "ir-manifest-coverage"
    description = ("every RecompileDetector-wrapped hot entry has a "
                   "manifest row in _lint_entries.py (anchored at the "
                   "detector construction site)")

    def check_entry(self, ctx, rel, entry, closed):
        return []  # emitted by run_ir_pass from the AST detector scan


def detector_sites(ctx: LintContext) -> List[Tuple[str, int, str]]:
    """(rel_path, line, group) for every RecompileDetector(...) call in
    the package whose name argument is a (possibly f-string) literal —
    the same names the cost model groups by (costmodel.group_of)."""
    sites: List[Tuple[str, int, str]] = []
    for pf in ctx.files:
        if pf.tree is None:
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call) or len(node.args) < 2:
                continue
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute)
                    else None)
            if name != "RecompileDetector":
                continue
            arg = node.args[1]
            head: Optional[str] = None
            if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                            str):
                head = arg.value
            elif isinstance(arg, ast.JoinedStr) and arg.values:
                first = arg.values[0]
                if isinstance(first, ast.Constant) and \
                        isinstance(first.value, str):
                    head = first.value
            if head:
                group = head.split("[", 1)[0]
                sites.append((pf.rel, node.lineno, group))
    return sites


def run_ir_pass(ctx: LintContext,
                rule_names: Optional[List[str]] = None,
                groups: Optional[List[str]] = None
                ) -> Tuple[List[Finding], int, Dict[str, str]]:
    """Load the manifest, trace each entry ONCE, and dispatch the named
    ir rules over the traced jaxprs.  Returns (findings, num_traced,
    {entry name: exemplar signature hash}) — the signatures key the
    per-entry result cache (core._ir_findings_and_section).  `groups`
    restricts tracing to the named detector groups (bench.py audits
    only the entries a run actually compiled)."""
    from ..core import RULES
    if rule_names is None:
        rule_names = [n for n in RULES if getattr(RULES[n], "ir", False)]
    mf_rel = manifest_rel(ctx)
    entries, err = load_manifest(ctx.package_dir)
    if err is not None:
        return [Finding(rule="ir-trace-error", path=mf_rel, line=1,
                        col=0, message=err)], 0, {}
    findings: List[Finding] = []
    if "ir-manifest-coverage" in rule_names:
        covered = {e.group for e in entries}
        seen = set()
        for rel, line, group in detector_sites(ctx):
            if group in covered or group in seen:
                continue
            seen.add(group)
            findings.append(Finding(
                rule="ir-manifest-coverage", path=rel, line=line, col=0,
                message=f"hot entry group '{group}' is "
                        "RecompileDetector-fingerprinted at runtime but "
                        f"has no entry in {mf_rel} — the IR audit "
                        "cannot see it"))
    per_entry_rules = [RULES[n] for n in rule_names
                       if n not in ("ir-manifest-coverage",
                                    "ir-trace-error")]
    num_traced = 0
    sigs: Dict[str, str] = {}
    for entry in entries:
        if groups is not None and entry.group not in groups:
            continue
        closed, sig, err = trace_entry(entry)
        if err is not None:
            if "ir-trace-error" in rule_names:
                findings.append(Finding(
                    rule="ir-trace-error", path=mf_rel,
                    line=getattr(entry, "line", 1), col=0,
                    message=f"[{entry.name}] {err}"))
            continue
        num_traced += 1
        sigs[entry.name] = sig
        for rule in per_entry_rules:
            findings.extend(rule.check_entry(ctx, mf_rel, entry, closed))
    return findings, num_traced, sigs
