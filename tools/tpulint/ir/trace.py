"""Manifest loading and abstract tracing for the IR rules.

The linted package declares its hot jitted entries in
`<package>/_lint_entries.py` (protocol documented there): each entry
names a RecompileDetector group, a zero-arg builder returning the
jitted callable plus exemplar `jax.ShapeDtypeStruct` arguments, and a
set of declared IR shapes.  This module turns an entry into a
ClosedJaxpr:

* tracing is ABSTRACT — `fn.trace(*args)` (jax AOT) with
  ShapeDtypeStruct leaves builds the jaxpr from avals alone; nothing
  touches a device and nothing compiles, so a full-package audit is
  seconds, not minutes;
* tracing runs under `jax.experimental.enable_x64`: with the default
  x64-off config jax silently clamps EVERY array to 32 bits, which
  would make `ir-no-f64` a tautology.  With x64 on, a float64 numpy
  constant or weak-type promotion in device code produces a float64
  aval in the jaxpr — exactly the latent 10–20× TPU hazard the rule
  exists to surface (it is latent: the same code run under x64, e.g.
  by an embedding application, double-widths the hot path);
* the exemplar signature is hashed with the SAME (shape, dtype,
  static) scheme RecompileDetector/CostModel fingerprint at runtime
  (observability/watchdog.py call_signature), and that hash keys the
  per-entry result cache in `.tpulint_cache.json`.

Failures are data, not crashes: a manifest that does not import, an
entry whose builder raises, or a trace error each become an
`ir-trace-error` finding anchored at the manifest.
"""

from __future__ import annotations

import hashlib
import importlib
import os
import sys
from typing import Any, Iterator, List, Optional, Tuple

MANIFEST_BASENAME = "_lint_entries.py"


def _pin_platform() -> None:
    """Honor JAX_PLATFORMS via jax.config BEFORE backend init: on hosts
    with an accelerator plugin that ignores the env var (the container's
    axon TPU plugin), a bare jax import hangs on backend discovery —
    the same workaround tests/conftest.py and bench.py use."""
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    try:
        import jax
        jax.config.update("jax_platforms", plat)
    except Exception:  # noqa: BLE001 - best-effort; import errors surface later
        pass


def manifest_rel(ctx) -> str:
    """Repo-relative path of the package's manifest (finding anchor)."""
    return os.path.join(ctx.package_name, MANIFEST_BASENAME)


def load_manifest(package_dir: str
                  ) -> Tuple[Optional[List], Optional[str]]:
    """Import `<package>._lint_entries` and return (entries, error).

    The package is imported for real (builders use relative imports),
    with its parent directory on sys.path — the same context the
    package runs under.  A missing manifest is an error string, not an
    exception: the caller turns it into an `ir-trace-error` finding."""
    package_dir = os.path.abspath(package_dir)
    pkg_name = os.path.basename(package_dir)
    path = os.path.join(package_dir, MANIFEST_BASENAME)
    if not os.path.exists(path):
        return None, (f"no IR entrypoint manifest: {pkg_name}/"
                      f"{MANIFEST_BASENAME} does not exist")
    parent = os.path.dirname(package_dir)
    _pin_platform()
    inserted = False
    if parent not in sys.path:
        sys.path.insert(0, parent)
        inserted = True
    try:
        mod = importlib.import_module(f"{pkg_name}._lint_entries")
    except Exception as e:  # noqa: BLE001 - any import failure is a finding
        return None, f"cannot import {pkg_name}._lint_entries: {e!r}"
    finally:
        if inserted:
            try:
                sys.path.remove(parent)
            except ValueError:
                pass
    entries = getattr(mod, "ENTRIES", None)
    if entries is None:
        return None, (f"{pkg_name}._lint_entries defines no ENTRIES "
                      "(see the manifest protocol in "
                      "docs/StaticAnalysis.md)")
    return list(entries), None


def _normalize_build(built) -> Tuple[Any, tuple, dict]:
    if isinstance(built, tuple):
        if len(built) == 3:
            fn, args, kwargs = built
            return fn, tuple(args), dict(kwargs)
        if len(built) == 2:
            fn, args = built
            return fn, tuple(args), {}
    return built, (), {}


def signature_of(args: tuple, kwargs: dict) -> Tuple[tuple, tuple]:
    """The RecompileDetector fingerprint of an exemplar call: ((shape,
    dtype) per array leaf, repr per static leaf) over the flattened
    (args, kwargs) pytree — byte-compatible with
    observability/watchdog.py call_signature so the cache key and the
    runtime watchdog can never disagree about what an entry's
    signature IS."""
    import jax
    leaves = jax.tree_util.tree_leaves((args, kwargs))
    arrays, static = [], []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            arrays.append((tuple(leaf.shape), str(leaf.dtype)))
        else:
            static.append(repr(leaf))
    return tuple(arrays), tuple(static)


def signature_hash(args: tuple, kwargs: dict) -> str:
    sig = signature_of(args, kwargs)
    return hashlib.sha256(repr(sig).encode()).hexdigest()[:16]


def trace_entry(entry) -> Tuple[Optional[Any], Optional[str],
                                Optional[str]]:
    """Abstractly trace one manifest entry.

    Returns (ClosedJaxpr, signature_hash, error): on success the error
    is None; on failure the jaxpr is None and the error is a one-line
    reason (builder exception, trace exception)."""
    _pin_platform()
    import jax
    from jax.experimental import enable_x64
    try:
        fn, args, kwargs = _normalize_build(entry.build())
    except Exception as e:  # noqa: BLE001 - builder failure is a finding
        return None, None, f"builder raised: {e!r}"
    try:
        sig = signature_hash(args, kwargs)
        with enable_x64():
            traced = fn if hasattr(fn, "trace") else jax.jit(fn)
            closed = traced.trace(*args, **kwargs).jaxpr
    except Exception as e:  # noqa: BLE001 - trace failure is a finding
        return None, None, f"abstract trace failed: {e!r}"
    return closed, sig, None


# --------------------------------------------------------------- walking
def _sub_jaxprs(params: dict) -> Iterator[Any]:
    """Jaxpr-like values nested in an eqn's params (pjit/scan/while/
    cond/custom_* all stash callee jaxprs there).  Duck-typed on
    `.eqns` / `.jaxpr` so no fragile jax-internal imports."""
    for v in params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for x in vals:
            if hasattr(x, "jaxpr") and hasattr(x.jaxpr, "eqns"):
                yield x.jaxpr            # ClosedJaxpr
            elif hasattr(x, "eqns"):
                yield x                  # Jaxpr


def iter_jaxprs(closed) -> Iterator[Any]:
    """Every (sub-)Jaxpr of a ClosedJaxpr, outermost first."""
    stack = [closed.jaxpr]
    seen = set()
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        yield j
        for eq in j.eqns:
            stack.extend(_sub_jaxprs(eq.params))


def iter_eqns(closed) -> Iterator[Any]:
    """Every equation of a ClosedJaxpr, sub-jaxprs included."""
    for j in iter_jaxprs(closed):
        for eq in j.eqns:
            yield eq


def aval_of(v):
    """The abstract value of a var or literal, or None."""
    return getattr(v, "aval", None)


def dtype_name(v) -> Optional[str]:
    aval = aval_of(v)
    dt = getattr(aval, "dtype", None)
    return None if dt is None else str(dt)
