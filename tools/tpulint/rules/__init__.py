"""tpulint rule modules — importing this package registers every rule.

Each module defines one or two `core.Rule` subclasses decorated with
`@core.register`; `core.run_lint` imports this package so the registry
is always complete.  To add a rule, drop a module here and import it
below (docs/StaticAnalysis.md "Adding a rule").
"""

from . import atomic_write    # noqa: F401
from . import bare_print      # noqa: F401
from . import collectives     # noqa: F401
from . import config_doc      # noqa: F401
from . import device_put      # noqa: F401
from . import donate          # noqa: F401
from . import donate_sharding  # noqa: F401
from . import donated_reuse   # noqa: F401
from . import dtype           # noqa: F401
from . import host_sync       # noqa: F401
from . import rng_discipline  # noqa: F401
from . import shape_taint     # noqa: F401
from . import signal_safety   # noqa: F401
from . import spmd            # noqa: F401
from . import thread_safety   # noqa: F401
# jaxpr-level rules (ISSUE 12): registered alongside the AST rules so
# --list-rules/SARIF see them, but selected only by --ir (or by name) —
# registration imports nothing heavy (jax loads lazily at trace time)
from ..ir import rules as _ir_rules  # noqa: F401
