"""Shared helpers for the v3 concurrency rules.

`signal-handler-safety` and `thread-shared-state` both need to answer
"what kind of synchronization object is this expression?" — a lock, a
queue, an event, a thread.  Typing is resolved three ways, in order:

1. **constructor-typed attributes**: `self._q = queue.Queue(...)` in a
   class body or any method records `attr_types["_q"] = "queue.Queue"`
   on the ClassInfo (callgraph v3), so `self._q.put(...)` resolves
   exactly;
2. **constructor-typed locals**: `q = queue.Queue()` inside the scanned
   function;
3. **name heuristics**: receivers whose name contains `lock`/`mutex`
   (locks), `queue`/a bare `q` (queues), `event` (events) — the
   fallback for objects typed in another module.  `all_tasks_done` /
   `not_empty` / `not_full` / `mutex` are queue.Queue's internal
   Condition/Lock attributes and count as locks.
"""

from __future__ import annotations

import ast
from typing import Dict, Optional

from ..callgraph import cached_walk

LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
              "threading.Semaphore", "threading.BoundedSemaphore",
              "multiprocessing.Lock", "multiprocessing.RLock",
              "Lock", "RLock", "Condition", "Semaphore"}
QUEUE_CTORS = {"queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
               "queue.SimpleQueue", "multiprocessing.Queue", "Queue",
               "LifoQueue", "PriorityQueue", "SimpleQueue"}
EVENT_CTORS = {"threading.Event", "multiprocessing.Event", "Event"}
THREAD_CTORS = {"threading.Thread", "Thread"}

# queue.Queue internals: acquiring these IS acquiring a lock
_LOCKISH_ATTRS = {"all_tasks_done", "not_empty", "not_full", "mutex"}


def kind_of_ctor(dotted: Optional[str]) -> Optional[str]:
    if dotted is None:
        return None
    if dotted in LOCK_CTORS:
        return "lock"
    if dotted in QUEUE_CTORS:
        return "queue"
    if dotted in EVENT_CTORS:
        return "event"
    if dotted in THREAD_CTORS:
        return "thread"
    return None


def kind_of_name(name: str) -> Optional[str]:
    low = name.lower().lstrip("_")
    if name in _LOCKISH_ATTRS or "lock" in low or "mutex" in low \
            or low in ("mu", "cv", "cond"):
        return "lock"
    if "queue" in low or low == "q":
        return "queue"
    if "event" in low:
        return "event"
    return None


def local_ctor_types(mi, fn_node: ast.AST) -> Dict[str, str]:
    """name -> kind for `q = queue.Queue()`-style locals of a function."""
    out: Dict[str, str] = {}
    for node in cached_walk(fn_node):
        if not isinstance(node, ast.Assign) \
                or not isinstance(node.value, ast.Call):
            continue
        kind = kind_of_ctor(mi.dotted_of(node.value.func))
        if kind is None:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out[t.id] = kind
    return out


def receiver_kind(mi, owner_class, local_types: Dict[str, str],
                  expr: ast.AST) -> Optional[str]:
    """'lock' | 'queue' | 'event' | 'thread' | None for the receiver of
    a method call (`<expr>.put(...)`) or a `with <expr>:` item."""
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls") \
                and owner_class is not None:
            t = kind_of_ctor(owner_class.find_attr_type(expr.attr))
            if t is not None:
                return t
        return kind_of_name(expr.attr)
    if isinstance(expr, ast.Name):
        if expr.id in local_types:
            return local_types[expr.id]
        return kind_of_name(expr.id)
    return None


def lock_token(expr: ast.AST) -> Optional[str]:
    """Stable identifier for a lock expression, so two `with self._mu:`
    blocks compare equal in the lockset analysis."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def has_bound(call: ast.Call, kwargs=("timeout",),
              flags=(("block", False), ("blocking", False))) -> bool:
    """Does this call carry a bound — a `timeout=` keyword or a
    non-blocking flag (`block=False` / `blocking=False`)?  A keyword
    whose VALUE the analysis cannot prove is unbounded counts as bounded
    (the caller thought about it); `timeout=None` literals do not."""
    for kw in call.keywords:
        if kw.arg in kwargs:
            if isinstance(kw.value, ast.Constant) and kw.value.value is None:
                continue
            return True
        for name, val in flags:
            if kw.arg == name and isinstance(kw.value, ast.Constant) \
                    and kw.value.value == val:
                return True
    return False
