"""atomic-write-discipline: reliability-critical files land whole or
not at all.

The PR-8 checkpoint manifest carries SHA-256 digests computed over "the
exact bytes handed to the atomic writer"; resume integrity, generation
fallback and the supervisor's stall/degrade state files all assume a
reader can never observe a half-written file.  `utils.atomic_write_text
/ atomic_write_bytes` (sibling temp file + `os.replace`) is the one
sanctioned write path; a direct `open(path, "w")` under `reliability/`
is a torn-file hazard that surfaces as a corrupt-checkpoint quarantine
(at best) or a resume from damage (at worst).

Flags `open(..., mode)` calls with a write-capable, non-append mode
(`w`, `wb`, `w+`, `r+`, ...) in files under `reliability/`.  Append
modes (`a`, `ab`) pass — the event log is append-only by design, and an
interrupted append loses one record, not the file.  Reads pass.  An
`open` inside a function that also calls `os.replace` or an
`atomic_write_*` helper passes too: that IS the inline atomic idiom
(tempfile + replace).  Deliberate in-place damage (fault injection's
`ckpt_corrupt`) suppresses with a justification.
"""

from __future__ import annotations

import ast
from typing import List

from ..callgraph import cached_walk, module_info_for
from ..core import Finding, LintContext, Rule, register

_SCOPE_PREFIXES = ("reliability", "online")
# terminal-artifact writers outside reliability/: the flight recorder's
# stall/crash/SIGUSR2 dumps are read by the same supervisor machinery
# as the stall diagnosis, so they obey the same torn-file discipline;
# the tracing layer joins the scope with it (assembled waterfalls ride
# the same dump path and must never land torn).  online/ is in scope
# because chunk files and published model paths are read by OTHER
# processes (the watcher, replica loads) — a torn write there serves
# a half-published model or trains on half a chunk.
_SCOPE_FILES = {"observability/flightrec.py",
                "observability/tracing.py"}
_WRITE_MODES = {"w", "wt", "wb", "w+", "wb+", "w+b", "r+", "r+b", "rb+",
                "x", "xb"}
_ATOMIC_MARKERS = {"os.replace", "atomic_write_text",
                   "atomic_write_bytes"}


def _in_scope(pkg_rel: str) -> bool:
    rel = pkg_rel.replace("\\", "/")
    parts = rel.split("/")
    return (parts[0] in _SCOPE_PREFIXES and len(parts) > 1) \
        or rel in _SCOPE_FILES


def _open_mode(call: ast.Call) -> str:
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return "r"


@register
class AtomicWriteDiscipline(Rule):
    name = "atomic-write-discipline"
    description = ("direct open(..., 'w') under reliability/ — "
                   "checkpoint/manifest/state files must go through the "
                   "temp+os.replace atomic writer the SHA-256 digests "
                   "assume")
    file_local = True

    def check_file(self, ctx: LintContext, pf) -> List[Finding]:
        out: List[Finding] = []
        if pf.tree is None or not _in_scope(pf.pkg_rel):
            return out
        mi = module_info_for(ctx, pf)
        # functions whose body uses the inline atomic idiom are clean
        atomic_fns = set()
        for fn in cached_walk(pf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in cached_walk(fn):
                if isinstance(node, ast.Call):
                    dotted = mi.dotted_of(node.func) or ""
                    if dotted in _ATOMIC_MARKERS \
                            or dotted.rsplit(".", 1)[-1] in _ATOMIC_MARKERS:
                        atomic_fns.add(id(fn))
                        break

        def enclosing_fn(target):
            found = [None]

            def rec(node, fn):
                if node is target:
                    found[0] = fn
                    return True
                for child in ast.iter_child_nodes(node):
                    nfn = child if isinstance(
                        child, (ast.FunctionDef,
                                ast.AsyncFunctionDef)) else fn
                    if rec(child, nfn):
                        return True
                return False

            rec(pf.tree, None)
            return found[0]

        for node in cached_walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (isinstance(node.func, ast.Name)
                    and node.func.id == "open"):
                continue
            mode = _open_mode(node).replace("t", "")
            if mode not in _WRITE_MODES:
                continue
            fn = enclosing_fn(node)
            if fn is not None and id(fn) in atomic_fns:
                continue  # the inline temp+os.replace idiom
            out.append(Finding(
                rule=self.name, path=pf.rel, line=node.lineno,
                col=node.col_offset,
                message=f"direct open(..., {mode!r}) under reliability/ "
                        "— a crash mid-write leaves a torn file that "
                        "the checkpoint digests will quarantine (or a "
                        "reader resumes from damage); route through "
                        "utils.atomic_write_text/bytes (temp + "
                        "os.replace)"))
        return out
