"""no-bare-print: all runtime output goes through utils.log or the
structured event log (observability/events.py), never bare print().

Ported from tools/check_no_bare_print.py (ISSUE 2 satellite; now an
ISSUE 3 rule), same rationale and whitelist: a bare print() bypasses
verbosity gating, the register_logger redirection the sklearn wrapper
relies on, and the rank-tagged event log — under multi-process SPMD it
also interleaves unsynchronized worker output.  The reference enforces
the same discipline with its Log:: macros (include/LightGBM/utils/
log.h).

Whitelist: utils/log.py, where print() IS the default stderr sink.
`sys.stderr.write` is not flagged (used by the crash-injection marker
in reliability/faults.py, which must bypass any registered logger
right before os._exit).
"""

from __future__ import annotations

import ast
import os
from typing import List

from ..callgraph import cached_walk
from ..core import Finding, LintContext, Rule, register

WHITELIST = {os.path.join("utils", "log.py")}


@register
class NoBarePrint(Rule):
    name = "no-bare-print"
    description = ("bare print() in the runtime package; route output "
                   "through utils.log or the event log")

    file_local = True

    def check_file(self, ctx: LintContext, pf) -> List[Finding]:
        out: List[Finding] = []
        if pf.tree is None or pf.pkg_rel in WHITELIST:
            return out
        for node in cached_walk(pf.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                out.append(Finding(
                    rule=self.name, path=pf.rel, line=node.lineno,
                    col=node.col_offset,
                    message="bare print() — route output through "
                            "utils.log or the event log"))
        return out
