"""collective-discipline: cross-device collectives only in parallel/ or
distributed.py.

Under SPMD every rank must issue the SAME collectives in the SAME order
or the mesh deadlocks (the reference centralizes this in Network::
Allreduce / ReduceScatter, src/network/network.cpp, for the same
reason).  Keeping `lax.psum`/`pmean`/`all_gather`/... inside the
parallel layer keeps collective ordering auditable in one place — a
psum buried in a learner helper is invisible to whoever reorders the
training loop.
"""

from __future__ import annotations

import ast
import os
from typing import List

from ..core import Finding, LintContext, Rule, register

COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
               "psum_scatter", "all_to_all", "ppermute"}
ALLOWED_DIRS = ("parallel",)
ALLOWED_FILES = {"distributed.py"}


def _is_allowed(pkg_rel: str) -> bool:
    parts = pkg_rel.split(os.sep)
    return parts[0] in ALLOWED_DIRS or pkg_rel in ALLOWED_FILES


@register
class CollectiveDiscipline(Rule):
    name = "collective-discipline"
    description = ("lax collective outside parallel/ or distributed.py; "
                   "SPMD collective ordering must stay auditable")

    file_local = True

    def check_file(self, ctx: LintContext, pf) -> List[Finding]:
        from ..callgraph import cached_walk, module_info_for
        out: List[Finding] = []
        if pf.tree is None or _is_allowed(pf.pkg_rel):
            return out
        mi = module_info_for(ctx, pf)
        for node in cached_walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = mi.dotted_of(node.func) or ""
            parts = dotted.rsplit(".", 1)
            if len(parts) == 2 and parts[1] in COLLECTIVES \
                    and parts[0] in ("jax.lax", "lax"):
                out.append(Finding(
                    rule=self.name, path=pf.rel, line=node.lineno,
                    col=node.col_offset,
                    message=f"lax.{parts[1]} outside parallel/ or "
                            "distributed.py — collectives live in the "
                            "parallel layer so SPMD ordering stays "
                            "auditable"))
        return out
