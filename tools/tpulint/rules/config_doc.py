"""config-doc-sync: config.py PARAMS and docs/Parameters.md must match.

The parameter table is the single source of truth (ref: the reference's
.ci/parameter-generator.py renders docs/Parameters.rst from config.h
doc-comments for the same reason).  tools/gen_params_doc.py REGENERATES
the doc; this rule VERIFIES the two never drift — a new Config field
without a doc row (or a stale doc row after a rename) fails lint, so
drift can't merge even when someone edits one side by hand.

Both sides are read statically: PARAMS via AST (no package import — the
lint must not need jax), the doc via the generated table's `| `name` |`
row shape.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List

from ..core import Finding, LintContext, Rule, register

_DOC_ROW_RE = re.compile(r"^\|\s*`(\w+)`\s*\|")


def params_from_config(pf) -> Dict[str, int]:
    """name -> lineno of every PARAMS entry in a parsed config.py."""
    out: Dict[str, int] = {}
    if pf is None or pf.tree is None:
        return out
    for node in pf.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "PARAMS"
                   for t in node.targets):
            continue
        if isinstance(node.value, (ast.List, ast.Tuple)):
            for elt in node.value.elts:
                if isinstance(elt, ast.Tuple) and elt.elts \
                        and isinstance(elt.elts[0], ast.Constant) \
                        and isinstance(elt.elts[0].value, str):
                    out[elt.elts[0].value] = elt.lineno
    return out


def params_from_doc(doc_path: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    with open(doc_path, encoding="utf-8") as f:
        for i, line in enumerate(f, start=1):
            m = _DOC_ROW_RE.match(line.strip())
            if m and m.group(1) != "Parameter":
                out[m.group(1)] = i
    return out


@register
class ConfigDocSync(Rule):
    name = "config-doc-sync"
    description = ("config.py PARAMS and docs/Parameters.md out of sync "
                   "(run tools/gen_params_doc.py)")

    def check(self, ctx: LintContext) -> List[Finding]:
        out: List[Finding] = []
        pf = ctx.file_by_pkg_rel("config.py")
        if pf is None:
            return out  # package without a config module: nothing to sync
        params = params_from_config(pf)
        if not params:
            return out
        doc_path = os.path.join(ctx.docs_dir, "Parameters.md")
        doc_rel = os.path.relpath(doc_path, ctx.root)
        if not os.path.exists(doc_path):
            out.append(Finding(
                rule=self.name, path=pf.rel, line=1, col=0,
                message=f"{doc_rel} is missing — run "
                        "tools/gen_params_doc.py"))
            return out
        doc = params_from_doc(doc_path)
        for name, lineno in params.items():
            if name not in doc:
                out.append(Finding(
                    rule=self.name, path=pf.rel, line=lineno, col=0,
                    message=f"Config field `{name}` is not documented in "
                            f"{doc_rel} — run tools/gen_params_doc.py"))
        for name, lineno in doc.items():
            if name not in params:
                out.append(Finding(
                    rule=self.name, path=doc_rel, line=lineno, col=0,
                    message=f"documented parameter `{name}` does not "
                            "exist in config.py PARAMS — stale doc row, "
                            "run tools/gen_params_doc.py"))
        return out
