"""no-device-put-in-loop: H2D transfers must not sit in Python loop bodies.

`jax.device_put` / `jnp.asarray` of host data costs a host->device
transfer (a full tunnel round trip on the remote-TPU runtime, ~100 ms
each; see boosting/gbdt.py's hot-path notes).  Inside a Python `for` /
`while` body that cost multiplies by the trip count and the dispatch
queue never pipelines — the classic accidental serializer, and exactly
the bug an inference batcher breeds: putting each request row / bucket
element individually instead of padding once and transferring once.

The rule is lexical: any `jax.device_put` or `jnp.asarray` call inside a
`for`/`while` statement body in device-code scope is flagged.  Loops
inside jitted code are traced (unrolled) rather than executed, and a
device_put there is a no-op — but device code here keeps jnp.asarray out
of trace bodies anyway, so the rule does not special-case them; suppress
with a justification for the rare intentional per-iteration put.
Comprehensions/generators are NOT matched (the ROADMAP'd rule targets
statement loops; a comprehension converting a handful of scalars is the
common benign form).

Scope: the same device-code modules as explicit-dtype — learner/, ops/,
parallel/, inference/, io/device_bin.py.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Finding, LintContext, Rule, register
from .dtype import _in_scope

_PUT_NAMES = {"jax.device_put", "jnp.asarray", "jax.numpy.asarray"}


@register
class NoDevicePutInLoop(Rule):
    name = "no-device-put-in-loop"
    description = ("jax.device_put/jnp.asarray inside a for/while body — "
                   "one H2D transfer per iteration serializes the loop")

    file_local = True

    def check_file(self, ctx: LintContext, pf) -> List[Finding]:
        from ..callgraph import cached_walk, module_info_for
        out: List[Finding] = []
        if pf.tree is None or not _in_scope(pf.pkg_rel):
            return out
        mi = module_info_for(ctx, pf)
        seen = set()
        for loop in cached_walk(pf.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in cached_walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                dotted = mi.dotted_of(node.func) or ""
                if dotted not in _PUT_NAMES:
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:  # nested loops walk the same call twice
                    continue
                seen.add(key)
                out.append(Finding(
                    rule=self.name, path=pf.rel, line=node.lineno,
                    col=node.col_offset,
                    message=f"{dotted} inside a {'for' if isinstance(loop, ast.For) else 'while'} "
                            "body — host->device transfers in loops "
                            "serialize on the dispatch queue; batch the "
                            "data and transfer once outside the loop"))
        return out
