"""donate-argnums: jitted entries taking score/gradient buffers must
donate them.

The training loop's big per-iteration arrays — the [K, n] score buffer
and the gradient/hessian maps — are rewritten every iteration.  A jitted
update that takes one of them WITHOUT `donate_argnums`/`donate_argnames`
forces XLA to allocate a fresh output buffer while the input stays live:
at 10M rows that is an extra [K, n_pad] f32 allocation per tree, HBM
the histogram stack could have used, plus a copy the aliasing pass would
have elided (jax docs: buffer donation).  This is the lint-time form of
the ROADMAP'd "score buffers should be donated in jit" follow-up.

The rule is NAME-based: a function wrapped in `jax.jit` (decorator,
`functools.partial(jax.jit, ...)` decorator, or the assignment form
`f = jax.jit(g, ...)` where `g`/a lambda is visible in the module) whose
parameters include one of `scores`/`grad`/`hess`/`gradients`/`hessians`
must cover every such parameter with `donate_argnums` (positional
index) or `donate_argnames`.  A donate keyword whose value is not a
literal tuple (a config-gated expression like
`donate_argnums=_donate0`) counts as covering — the donation decision
is then runtime configuration, which is exactly the sanctioned escape
hatch.  Genuinely read-only consumers (eval reductions, sentinel flag
folds, gradient maps whose caller keeps the scores) suppress with a
justification, keeping the audit trail.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from ..callgraph import cached_walk, module_info_for
from ..core import Finding, LintContext, Rule, register

# canonical buffer parameter names the training loop uses
DONATABLE = {"scores", "grad", "hess", "gradients", "hessians"}


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in getattr(a, "posonlyargs", [])]
    names += [p.arg for p in a.args]
    return names


def _donate_spec(call: ast.Call) -> Optional[Tuple[Set[int], Set[str],
                                                   bool]]:
    """(indices, names, is_literal) from a jit call's donate keywords;
    None when no donate keyword is present."""
    found = False
    idxs: Set[int] = set()
    names: Set[str] = set()
    literal = True
    for kw in call.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        found = True
        consts = [v for v in cached_walk(kw.value)
                  if isinstance(v, ast.Constant)]
        if isinstance(kw.value, (ast.Tuple, ast.List, ast.Constant)):
            for v in consts:
                if isinstance(v.value, int) and not isinstance(v.value,
                                                               bool):
                    idxs.add(v.value)
                elif isinstance(v.value, str):
                    names.add(v.value)
        else:
            # non-literal (config-gated) donate expression: trust it
            literal = False
    return (idxs, names, literal) if found else None


@register
class DonateArgnums(Rule):
    name = "donate-argnums"
    description = ("jitted entries taking score/gradient buffers must "
                   "donate them (donate_argnums) so XLA reuses the HBM "
                   "instead of allocating a fresh output buffer")

    file_local = True

    def check_file(self, ctx: LintContext, pf) -> List[Finding]:
        out: List[Finding] = []
        if pf.tree is None:
            return out
        mi = module_info_for(ctx, pf)
        for node in cached_walk(pf.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    jit_call = self._as_jit_call(mi, dec)
                    if jit_call is not None:
                        out.extend(self._check_entry(
                            pf, node, jit_call[0], jit_call[1]))
            elif isinstance(node, ast.Call) \
                    and self._is_jit_name(mi, node.func) and node.args:
                target = node.args[0]
                fn = None
                if isinstance(target, ast.Lambda):
                    fn = target
                elif isinstance(target, ast.Name):
                    fn = self._find_def(pf.tree, target.id)
                if fn is not None:
                    out.extend(self._check_entry(pf, fn, node,
                                                 node.lineno))
        return out

    # ---- helpers -----------------------------------------------------
    def _is_jit_name(self, mi, expr: ast.AST) -> bool:
        return mi.dotted_of(expr) in ("jax.jit", "jit")

    def _as_jit_call(self, mi, dec: ast.AST):
        """(call_node, report_line) when `dec` is a jit decorator that
        can carry donate keywords; None otherwise.  A bare `@jax.jit`
        is a Name/Attribute (no keywords possible)."""
        if isinstance(dec, ast.Call):
            if self._is_jit_name(mi, dec.func):
                return dec, dec.lineno
            dotted = mi.dotted_of(dec.func)
            if dotted in ("functools.partial", "partial") and dec.args \
                    and self._is_jit_name(mi, dec.args[0]):
                return dec, dec.lineno
        elif self._is_jit_name(mi, dec):
            # bare @jax.jit: treat as a donate-less jit call
            return ast.Call(func=dec, args=[], keywords=[]), dec.lineno
        return None

    def _find_def(self, tree: ast.AST, name: str) -> Optional[ast.AST]:
        for node in cached_walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == name:
                return node
        return None

    def _check_entry(self, pf, fn: ast.AST, call: ast.Call,
                     line: int) -> List[Finding]:
        params = _param_names(fn)
        hits = [(i, p) for i, p in enumerate(params) if p in DONATABLE]
        if not hits:
            return []
        spec = _donate_spec(call)
        missing = []
        for i, p in hits:
            if spec is None:
                missing.append(p)
                continue
            idxs, names, literal = spec
            if not literal or i in idxs or p in names:
                continue
            missing.append(p)
        if not missing:
            return []
        return [Finding(
            rule=self.name, path=pf.rel, line=line, col=0,
            message=f"jitted entry takes buffer parameter(s) "
                    f"{', '.join(repr(m) for m in missing)} without "
                    "donating them — add donate_argnums/donate_argnames "
                    "(XLA then reuses the input HBM for the output) or "
                    "suppress with a justification if the caller keeps "
                    "the buffer")]
