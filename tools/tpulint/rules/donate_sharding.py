"""donated-sharding: donated shard_map entries need explicit shardings.

Donating a buffer into a `jax.jit(shard_map(...))` entry WITHOUT
explicit `in_shardings` leaves XLA to infer the donated layout from
the runtime arguments.  On a multi-device mesh the inferred sharding
can disagree with what the aliasing pass needs, so the donation is
silently dropped ("Some donated buffers were not usable") at best and
destabilizes the multi-device compile at worst — the donation x SPMD
interaction implicated in the MULTICHIP_r05 timeout.
`parallel/data_parallel.py` now passes explicit shardings on its
donate path and `boosting/gbdt.py` gates grow-buffer donation off
under a mesh; this rule keeps both invariants from regressing.

Flags: `jax.jit(<shard_map result>, donate_argnums=...)` (or
`donate_argnames`) where the donate spec is not the literal empty
tuple and no `in_shardings` keyword is present.  The shard_map result
is recognized directly (`jax.jit(shard_map(...), ...)`) or through a
local/module binding (`mapped = shard_map(...); jax.jit(mapped, ...)`).
Config-gated specs (`donate_argnums=(1, 2) if donate else ()`) count
as donating: the entry must be safe when the configuration turns
donation ON.
"""

from __future__ import annotations

import ast
from typing import List

from ..callgraph import cached_walk, module_info_for
from ..core import Finding, LintContext, Rule, register
from .spmd import _is_shard_map_call


@register
class DonatedSharding(Rule):
    name = "donated-sharding"
    description = ("jax.jit over a shard_map'd entry donates buffers "
                   "without explicit in_shardings — XLA infers the "
                   "donated layout from the arguments (MULTICHIP_r05)")

    file_local = True

    def check_file(self, ctx: LintContext, pf) -> List[Finding]:
        out: List[Finding] = []
        if pf.tree is None:
            return out
        self._check_module(module_info_for(ctx, pf), out)
        return out

    def _check_module(self, mi, out: List[Finding]) -> None:
        # names bound to a shard_map(...) result anywhere in the module
        # (module level or function-local)
        sm_names = set()
        for node in cached_walk(mi.pf.tree):
            if isinstance(node, ast.Assign) \
                    and _is_shard_map_call(mi, node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        sm_names.add(t.id)
        for node in cached_walk(mi.pf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if mi.dotted_of(node.func) not in ("jax.jit", "jit"):
                continue
            target = node.args[0]
            is_sm = _is_shard_map_call(mi, target) or (
                isinstance(target, ast.Name) and target.id in sm_names)
            if not is_sm:
                continue
            donate_kw = [kw for kw in node.keywords
                         if kw.arg in ("donate_argnums",
                                       "donate_argnames")]
            if not donate_kw:
                continue
            maybe_donates = any(
                not (isinstance(kw.value, (ast.Tuple, ast.List))
                     and not kw.value.elts)
                for kw in donate_kw)
            has_shardings = any(kw.arg == "in_shardings"
                                for kw in node.keywords)
            if maybe_donates and not has_shardings:
                out.append(Finding(
                    rule=self.name, path=mi.pf.rel,
                    line=node.lineno, col=node.col_offset,
                    message="jax.jit over a shard_map'd entry donates "
                            "buffers without explicit in_shardings — "
                            "XLA then infers the donated layout from "
                            "the arguments (the donation x SPMD "
                            "interaction implicated in MULTICHIP_r05); "
                            "pass in_shardings for every donated "
                            "argument or drop the donation"))
