"""donated-buffer-reuse: reading a buffer after donating it to a jit.

`donate_argnums` tells XLA the argument's HBM may be reused: after the
donating call, the caller's array is DELETED — a later read raises
"Array has been deleted", or worse, on some backends silently reads
reused pages.  PR 5's `tpu_donate_buffers` introduced exactly this
hazard class around the score/grad/hess buffers, and the donate-
argnums rule only checks that entries donate — not that callers stop
using what they donated.  This rule closes the caller side.

Mechanics:

* **donated entries** are collected package-wide: `@jax.jit(...,
  donate_argnums=...)` decorators, `f = jax.jit(g, donate_argnums=...)`
  assignments, and `self._fn = jax.jit(g, ...)` attributes — including
  config-gated specs (`donate_argnums=_donate0` where `_donate0` is
  `(0,) if cfg else ()`: donation then depends on runtime
  configuration, and the caller must be safe when it is ON).  The
  donating property propagates through rebinding — `self._grow_fn =
  donated_entry if flag else plain_entry` and wrapper calls
  (`RecompileDetector(self._grow_fn)`) keep the donated positions, and
  import/re-export chains are followed.

* **call sites**: inside every package function, a call resolving to a
  donated entry consumes the bindings passed in donated positions
  (names and `self.attr` attributes).  Simple aliases are tracked —
  `gq, hq = g_k, h_k` followed by donating `gq` consumes `g_k` too.

* a read of a consumed binding in a LATER statement (before it is
  rebound) is a finding.  `scores = update(scores, ...)` — the
  idiomatic donate-and-rebind — is clean: the statement's own target
  rebinds the name.  Branches are analyzed separately and merged
  conservatively (consumed in either branch counts).  Loop-carried
  reuse (consume at the bottom of a body, read at the top of the next
  iteration) is out of scope; the fixture tests pin the contract.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, LintContext, Rule, register
from ..callgraph import cached_walk
from .host_sync import _analyze


@dataclass
class DonateSpec:
    """Donated parameter positions/names of one jitted entry."""
    idxs: Set[int] = field(default_factory=set)
    names: Set[str] = field(default_factory=set)
    source: str = "jitted entry"

    def merged(self, other: "DonateSpec") -> "DonateSpec":
        return DonateSpec(self.idxs | other.idxs,
                          self.names | other.names,
                          self.source if self.idxs or self.names
                          else other.source)

    def __bool__(self) -> bool:
        return bool(self.idxs or self.names)


def _const_ints_strs(expr: ast.AST) -> Tuple[Set[int], Set[str]]:
    idxs: Set[int] = set()
    names: Set[str] = set()
    for v in cached_walk(expr):
        if isinstance(v, ast.Constant):
            if isinstance(v.value, bool):
                continue
            if isinstance(v.value, int):
                idxs.add(v.value)
            elif isinstance(v.value, str):
                names.add(v.value)
    return idxs, names


class _DonatedIndex:
    """Package-wide map of donated entries: module names, class attrs."""

    def __init__(self, ctx, index):
        self.index = index
        # (module_dotted, name) -> DonateSpec
        self.by_name: Dict[Tuple[str, str], DonateSpec] = {}
        # (module_dotted, class_name, attr) -> DonateSpec
        self.by_attr: Dict[Tuple[str, str, str], DonateSpec] = {}
        # def node id -> DonateSpec (decorated functions)
        self.by_def: Dict[int, DonateSpec] = {}
        for mi in index.modules.values():
            if mi.pf.tree is not None:
                self._scan_module(mi)
        self._propagate()

    # ---- collection ---------------------------------------------------
    def _scan_module(self, mi) -> None:
        for node in cached_walk(mi.pf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    spec = self._spec_of_jit_call(mi, dec, node)
                    if spec:
                        spec.source = f"`{node.name}`"
                        self.by_def[id(node)] = spec
                        self.by_name[(mi.dotted, node.name)] = spec
            elif isinstance(node, ast.Assign):
                spec = self._spec_of_expr(mi, node.value)
                if not spec:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        spec.source = f"`{t.id}`"
                        self.by_name[(mi.dotted, t.id)] = spec
                    else:
                        attr = self._self_attr(t)
                        cls = self._owning_class(mi, node)
                        if attr and cls:
                            spec.source = f"`self.{attr}`"
                            key = (mi.dotted, cls, attr)
                            self.by_attr[key] = spec.merged(
                                self.by_attr.get(key, DonateSpec()))

    def _owning_class(self, mi, node: ast.AST) -> Optional[str]:
        for ci in mi.top_classes.values():
            for n in cached_walk(ci.node):
                if n is node:
                    return ci.name
        return None

    @staticmethod
    def _self_attr(t: ast.AST) -> Optional[str]:
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id in ("self", "cls"):
            return t.attr
        return None

    def _spec_of_jit_call(self, mi, call: ast.AST,
                          fn: Optional[ast.AST]) -> Optional[DonateSpec]:
        """Donate spec carried by a jit/partial(jit) call expression."""
        if not isinstance(call, ast.Call):
            return None
        dotted = mi.dotted_of(call.func)
        is_jit = dotted in ("jax.jit", "jit")
        if not is_jit and dotted in ("functools.partial", "partial") \
                and call.args:
            is_jit = mi.dotted_of(call.args[0]) in ("jax.jit", "jit")
        if not is_jit:
            return None
        spec = DonateSpec()
        for kw in call.keywords:
            if kw.arg not in ("donate_argnums", "donate_argnames"):
                continue
            expr = kw.value
            if isinstance(expr, ast.Name):
                # config-gated: _donate0 = (0,) if cfg else ()
                for e in self._name_assignments(mi, expr.id):
                    i, s = _const_ints_strs(e)
                    spec.idxs |= i
                    spec.names |= s
            else:
                i, s = _const_ints_strs(expr)
                spec.idxs |= i
                spec.names |= s
        if spec and fn is None and call.args:
            fn = self._jit_target(mi, call.args[0])
        if spec and fn is not None:
            self._names_to_idxs(spec, fn)
        return spec if spec else None

    def _jit_target(self, mi, target: ast.AST) -> Optional[ast.AST]:
        if isinstance(target, ast.Lambda):
            return target
        if isinstance(target, ast.Name):
            for fid in self.index.resolve_name(mi, target.id):
                return self.index.func(fid).node
        return None

    @staticmethod
    def _names_to_idxs(spec: DonateSpec, fn: ast.AST) -> None:
        a = fn.args
        params = [p.arg for p in getattr(a, "posonlyargs", [])]
        params += [p.arg for p in a.args]
        for n in list(spec.names):
            if n in params:
                spec.idxs.add(params.index(n))
                spec.names.discard(n)

    def _name_assignments(self, mi, name: str) -> List[ast.AST]:
        out = []
        for node in cached_walk(mi.pf.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        out.append(node.value)
        return out

    def _spec_of_expr(self, mi, expr: ast.AST,
                      cls: Optional[str] = None) -> DonateSpec:
        """Donated positions an assignment RHS may carry: a direct jit
        call, references to donated names/attrs, wrapper-call args."""
        spec = DonateSpec()
        direct = self._spec_of_jit_call(mi, expr, None)
        if direct:
            return direct
        for node in cached_walk(expr):
            if isinstance(node, ast.Name):
                s = self.resolve_name_spec(mi, node.id)
                if s:
                    spec = spec.merged(s)
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in ("self", "cls") and cls:
                s = self.by_attr.get((mi.dotted, cls, node.attr))
                if s:
                    spec = spec.merged(s)
        return spec

    def resolve_name_spec(self, mi, name: str,
                          _seen: Optional[Set[Tuple[str, str]]] = None
                          ) -> Optional[DonateSpec]:
        """Follow import/re-export chains to a donated module name."""
        _seen = _seen or set()
        key = (mi.dotted, name)
        if key in _seen:
            return None
        _seen.add(key)
        if key in self.by_name:
            return self.by_name[key]
        imp = mi.imports.get(name)
        if imp and imp[1]:
            tgt = self.index.modules.get(imp[0])
            if tgt is not None:
                return self.resolve_name_spec(tgt, imp[1], _seen)
        return None

    def _propagate(self) -> None:
        """self.attr = <expr referencing a donated entry> — fixpoint so
        wrapper rebinds (RecompileDetector(self._grow_fn)) keep it."""
        for _ in range(4):
            changed = False
            for mi in self.index.modules.values():
                if mi.pf.tree is None:
                    continue
                for ci in mi.top_classes.values():
                    for node in cached_walk(ci.node):
                        if not isinstance(node, ast.Assign):
                            continue
                        for t in node.targets:
                            attr = self._self_attr(t)
                            if attr is None:
                                continue
                            spec = self._spec_of_expr(mi, node.value,
                                                      ci.name)
                            if not spec:
                                continue
                            key = (mi.dotted, ci.name, attr)
                            cur = self.by_attr.get(key)
                            new = spec.merged(cur or DonateSpec())
                            if cur is None or new.idxs != cur.idxs \
                                    or new.names != cur.names:
                                self.by_attr[key] = new
                                changed = True
            if not changed:
                break

    # ---- call-site lookup --------------------------------------------
    def spec_for_call(self, mi, cls: Optional[str],
                      func: ast.AST) -> Optional[DonateSpec]:
        if isinstance(func, ast.Name):
            return self.resolve_name_spec(mi, func.id)
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) \
                    and func.value.id in ("self", "cls"):
                if cls is not None:
                    s = self.by_attr.get((mi.dotted, cls, func.attr))
                    if s:
                        return s
                    # inherited attributes (RF(GBDT) uses the base's
                    # _score_update_fn)
                    ci = mi.top_classes.get(cls)
                    for base in (ci.bases if ci else []):
                        s = self.by_attr.get((base.module.dotted,
                                              base.name, func.attr))
                        if s:
                            return s
                return None
            if isinstance(func.value, ast.Name):
                imp = mi.imports.get(func.value.id)
                if imp and imp[1] is None:
                    tgt = self.index.modules.get(imp[0])
                    if tgt is not None:
                        return self.resolve_name_spec(tgt, func.attr)
        return None


# ---------------------------------------------------------------- walker
def _binding_key(expr: ast.AST) -> Optional[str]:
    """Name or self.attr expression -> binding key string."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                      ast.Name) \
            and expr.value.id in ("self", "cls"):
        return f"self.{expr.attr}"
    return None


class _State:
    """consumed binding -> (entry description, donate line);
    alias groups are shared sets of binding keys."""

    def __init__(self):
        self.consumed: Dict[str, Tuple[str, int]] = {}
        self.alias: Dict[str, Set[str]] = {}

    def copy(self) -> "_State":
        s = _State()
        s.consumed = dict(self.consumed)
        s.alias = {k: set(v) for k, v in self.alias.items()}
        return s

    def merge(self, other: "_State") -> None:
        self.consumed.update(other.consumed)
        for k, v in other.alias.items():
            self.alias.setdefault(k, set()).update(v)

    def group(self, key: str) -> Set[str]:
        return self.alias.get(key, set()) | {key}

    def consume(self, key: str, why: Tuple[str, int]) -> None:
        for k in self.group(key):
            self.consumed[k] = why

    def rebind(self, key: str) -> None:
        self.consumed.pop(key, None)
        grp = self.alias.pop(key, None)
        if grp is not None:
            for other in grp:
                self.alias.get(other, set()).discard(key)

    def record_alias(self, a: str, b: str) -> None:
        grp = self.alias.setdefault(a, set())
        grp.add(b)
        self.alias.setdefault(b, set()).add(a)


@register
class DonatedBufferReuse(Rule):
    name = "donated-buffer-reuse"
    description = ("a binding passed in a donated position of a jitted "
                   "entry is read again before being rebound — donation "
                   "deletes the caller's buffer")

    def check(self, ctx: LintContext) -> List[Finding]:
        index, _ = _analyze(ctx)
        donated = _DonatedIndex(ctx, index)
        out: List[Finding] = []
        for mi in index.modules.values():
            if mi.pf.tree is None:
                continue
            for fi in list(mi.top_funcs.values()):
                if isinstance(fi.node, ast.Lambda):
                    continue
                self._check_function(mi, None, fi.node, donated, out)
            for ci in mi.top_classes.values():
                for m in ci.methods.values():
                    self._check_function(mi, ci.name, m.node, donated,
                                         out)
        return out

    # ---- one function -------------------------------------------------
    def _check_function(self, mi, cls: Optional[str], fn: ast.AST,
                        donated: _DonatedIndex,
                        out: List[Finding]) -> None:
        state = _State()
        self._walk_body(mi, cls, list(fn.body), state, donated, out)

    def _walk_body(self, mi, cls, body: List[ast.stmt], state: _State,
                   donated: _DonatedIndex, out: List[Finding]) -> None:
        for stmt in body:
            self._walk_stmt(mi, cls, stmt, state, donated, out)

    def _walk_stmt(self, mi, cls, stmt: ast.stmt, state: _State,
                   donated: _DonatedIndex, out: List[Finding]) -> None:
        if isinstance(stmt, ast.If):
            self._check_reads(mi, stmt.test, state, out)
            s1, s2 = state.copy(), state.copy()
            self._walk_body(mi, cls, stmt.body, s1, donated, out)
            self._walk_body(mi, cls, stmt.orelse, s2, donated, out)
            state.consumed = {}
            state.alias = {}
            state.merge(s1)
            state.merge(s2)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._check_reads(mi, stmt.iter, state, out)
            self._apply_targets(stmt.target, state)
            self._walk_body(mi, cls, stmt.body, state, donated, out)
            self._walk_body(mi, cls, stmt.orelse, state, donated, out)
            return
        if isinstance(stmt, ast.While):
            self._check_reads(mi, stmt.test, state, out)
            self._walk_body(mi, cls, stmt.body, state, donated, out)
            self._walk_body(mi, cls, stmt.orelse, state, donated, out)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_reads(mi, item.context_expr, state, out)
                if item.optional_vars is not None:
                    self._apply_targets(item.optional_vars, state)
            self._walk_body(mi, cls, stmt.body, state, donated, out)
            return
        if isinstance(stmt, ast.Try):
            self._walk_body(mi, cls, stmt.body, state, donated, out)
            for h in stmt.handlers:
                self._walk_body(mi, cls, h.body, state, donated, out)
            self._walk_body(mi, cls, stmt.orelse, state, donated, out)
            self._walk_body(mi, cls, stmt.finalbody, state, donated, out)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested scopes are checked as their own functions

        # simple statement: reads -> donations -> (re)bindings
        self._check_reads(mi, stmt, state, out)
        for call in self._calls_in(stmt):
            spec = donated.spec_for_call(mi, cls, call.func)
            if spec is None or not spec:
                continue
            for key in self._donated_arg_keys(call, spec):
                state.consume(key, (spec.source, call.lineno))
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                self._apply_targets(t, state)
            self._record_aliases(stmt, state)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            self._apply_targets(stmt.target, state)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                key = _binding_key(t)
                if key:
                    state.rebind(key)

    # ---- helpers ------------------------------------------------------
    @staticmethod
    def _calls_in(stmt: ast.stmt):
        for node in cached_walk(stmt):
            if isinstance(node, ast.Call):
                yield node

    @staticmethod
    def _donated_arg_keys(call: ast.Call, spec: DonateSpec):
        for i, a in enumerate(call.args):
            if i in spec.idxs and not isinstance(a, ast.Starred):
                key = _binding_key(a)
                if key:
                    yield key
        for kw in call.keywords:
            if kw.arg and kw.arg in spec.names:
                key = _binding_key(kw.value)
                if key:
                    yield key

    def _check_reads(self, mi, node: ast.AST, state: _State,
                     out: List[Finding]) -> None:
        if not state.consumed:
            return
        pf = mi.pf
        for n in cached_walk(node):
            key = None
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                key = n.id
            elif isinstance(n, ast.Attribute) \
                    and isinstance(n.ctx, ast.Load):
                key = _binding_key(n)
            if key is None or key not in state.consumed:
                continue
            source, line = state.consumed[key]
            out.append(Finding(
                rule=self.name, path=pf.rel, line=n.lineno,
                col=n.col_offset,
                message=f"`{key}` was passed in a donated position of "
                        f"{source} at line {line}; donation deletes the "
                        "caller's buffer, so this read can raise 'Array "
                        "has been deleted' (or read reused pages) — "
                        "move the read before the donating call, or "
                        "rebind the name first"))
            # one finding per consumption is enough
            state.rebind(key)

    def _apply_targets(self, target: ast.AST, state: _State) -> None:
        for n in cached_walk(target):
            if isinstance(n, (ast.Name, ast.Attribute)):
                key = _binding_key(n)
                if key and isinstance(getattr(n, "ctx", None),
                                      (ast.Store, ast.Del)):
                    state.rebind(key)

    def _record_aliases(self, stmt: ast.Assign, state: _State) -> None:
        for t in stmt.targets:
            if isinstance(t, (ast.Tuple, ast.List)) \
                    and isinstance(stmt.value, (ast.Tuple, ast.List)) \
                    and len(t.elts) == len(stmt.value.elts):
                for te, ve in zip(t.elts, stmt.value.elts):
                    tk, vk = _binding_key(te), _binding_key(ve)
                    if tk and vk:
                        state.record_alias(tk, vk)
            else:
                tk, vk = _binding_key(t), _binding_key(stmt.value)
                if tk and vk:
                    state.record_alias(tk, vk)
