"""explicit-dtype: array constructors in device code must pass a dtype.

A dtype-less `jnp.zeros(n)` is float32 but WEAK-typed: mixed into an
expression it can silently promote the whole computation (or flip the
result's weak-type flag, which changes the jit cache key and triggers a
recompile — exactly what PR 2's RecompileDetector fires on at runtime).
`jnp.arange(n)` similarly weak-types to int32/float32 by value.  In the
hot tree-growth path every such literal is a latent recompile or an
accidental f64/i64 promotion under `jax_enable_x64`, so device code
spells dtypes out.

Scope: learner/, ops/, parallel/, inference/, serving/, online/,
io/device_bin.py, plus the observability modules that sit against the
device runtime (costmodel.py harvests lowered programs, watchdog.py
fingerprints jitted calls) — the modules whose arrays feed jitted
programs (serving/ coalesces and dispatches request buckets through
them; online/ feeds chunks into training and probe rows into the
serving dispatch).  Host-side code (metrics, plotting, IO parsing) may
rely on NumPy-style defaults.
"""

from __future__ import annotations

import ast
import os
from typing import List

from ..core import Finding, LintContext, Rule, register

# constructor -> number of positional args that includes a positional
# dtype (e.g. jnp.zeros(shape, dtype) -> 2)
CONSTRUCTORS = {"zeros": 2, "ones": 2, "full": 3, "arange": 4,
                "array": 2, "empty": 2, "eye": 3}
SCOPE_DIRS = ("learner", "ops", "parallel", "inference", "serving",
              "online")
SCOPE_FILES = {os.path.join("io", "device_bin.py"),
               os.path.join("observability", "costmodel.py"),
               os.path.join("observability", "watchdog.py"),
               os.path.join("observability", "tracing.py")}


def _in_scope(pkg_rel: str) -> bool:
    parts = pkg_rel.split(os.sep)
    return parts[0] in SCOPE_DIRS or pkg_rel in SCOPE_FILES


@register
class ExplicitDtype(Rule):
    name = "explicit-dtype"
    description = ("jnp array constructor without an explicit dtype in "
                   "device code (weak-type promotion / recompile hazard)")

    file_local = True

    def check_file(self, ctx: LintContext, pf) -> List[Finding]:
        from ..callgraph import cached_walk, module_info_for
        out: List[Finding] = []
        if pf.tree is None or not _in_scope(pf.pkg_rel):
            return out
        mi = module_info_for(ctx, pf)
        for node in cached_walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = mi.dotted_of(node.func) or ""
            parts = dotted.rsplit(".", 1)
            if len(parts) != 2 or parts[0] not in ("jax.numpy", "jnp"):
                continue
            fn = parts[1]
            if fn not in CONSTRUCTORS:
                continue
            if any(kw.arg == "dtype" for kw in node.keywords):
                continue
            n_pos = len([a for a in node.args
                         if not isinstance(a, ast.Starred)])
            if n_pos >= CONSTRUCTORS[fn] and n_pos == len(node.args):
                continue  # positional dtype present
            out.append(Finding(
                rule=self.name, path=pf.rel, line=node.lineno,
                col=node.col_offset,
                message=f"jnp.{fn} without an explicit dtype — "
                        "weak-typed literals promote silently and "
                        "can flip the jit cache key"))
        return out
