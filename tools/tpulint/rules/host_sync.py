"""no-host-sync-in-jit and no-tracer-branch: the jit purity rules.

Both rules consume the static jit call graph + parameter taint built by
callgraph.py: every function wrapped in `jax.jit` (or reachable from
one through in-package calls with traced arguments) is device code, and
values derived from its non-static parameters are tracers.

* **no-host-sync-in-jit** flags concretizations of a tracer —
  `float(x)`, `int(x)`, `bool(x)`, `.item()`, `.tolist()`,
  `np.asarray(x)` / `np.array(x)`, `.block_until_ready()`.  Inside jit
  these either raise TracerConversionError at trace time or, worse,
  silently constant-fold a value that should be traced; on the hot path
  each one is a device round trip (SURVEY.md §3.3: the CUDA learner's
  per-split D2H sync is the thing the TPU port exists to avoid).

* **no-tracer-branch** flags Python control flow on a tracer — `if`/
  `while`/`assert`/ternary on a traced value.  Data-dependent control
  flow must use `lax.cond`/`lax.while_loop`/`jnp.where`; a Python
  branch either fails to trace or silently specializes the program to
  one side.  Branching on static parameters (`static_argnames`), on
  `.shape`/`.dtype`/`.ndim`, and `is`/`is not None` checks are all
  fine and not flagged.
"""

from __future__ import annotations

import ast
from typing import List

from ..callgraph import cached_walk, PackageIndex, build_reachable
from ..core import Finding, LintContext, Rule, register

SYNC_BUILTINS = {"float", "int", "bool", "complex"}
SYNC_METHODS = {"item", "tolist", "block_until_ready"}
NUMPY_MODULES = ("numpy", "np")
NUMPY_FUNCS = {"asarray", "array"}


def _analyze(ctx: LintContext):
    """Build (and cache on ctx) the analyzed jit-reachable functions."""
    cached = getattr(ctx, "_tpulint_reachable", None)
    if cached is None:
        index = PackageIndex(ctx)
        cached = (index, build_reachable(index))
        ctx._tpulint_reachable = cached  # type: ignore[attr-defined]
    return cached


def _for_each_function(ctx, visit):
    _, funcs = _analyze(ctx)
    seen_nodes = set()
    for fi in funcs:
        if id(fi.node) in seen_nodes:
            continue
        seen_nodes.add(id(fi.node))
        walker = getattr(fi, "_walker", None)
        if walker is None:
            continue
        visit(fi, walker)


@register
class NoHostSyncInJit(Rule):
    name = "no-host-sync-in-jit"
    description = ("host synchronization (float/int/bool/.item/"
                   "np.asarray/.block_until_ready) on a traced value "
                   "inside jit-reachable code")

    def check(self, ctx: LintContext) -> List[Finding]:
        out: List[Finding] = []

        def visit(fi, walker):
            pf = fi.module.pf
            for node in cached_walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                msg = None
                if isinstance(node.func, ast.Name) \
                        and node.func.id in SYNC_BUILTINS \
                        and node.args and walker.taint(node.args[0]):
                    msg = (f"{node.func.id}() concretizes a traced value "
                           "inside jit — keep it on device (jnp ops / "
                           "astype) or hoist it out of the jitted region")
                elif isinstance(node.func, ast.Attribute):
                    if node.func.attr in SYNC_METHODS \
                            and walker.taint(node.func.value):
                        msg = (f".{node.func.attr}() on a traced value "
                               "inside jit — a host sync / trace error")
                    else:
                        dotted = fi.module.dotted_of(node.func) or ""
                        parts = dotted.rsplit(".", 1)
                        if len(parts) == 2 \
                                and parts[0] in NUMPY_MODULES \
                                and parts[1] in NUMPY_FUNCS \
                                and node.args \
                                and walker.taint(node.args[0]):
                            msg = (f"np.{parts[1]}() on a traced value "
                                   "inside jit pulls it to the host — "
                                   "use jnp.asarray or keep the value "
                                   "traced")
                if msg is not None:
                    out.append(Finding(
                        rule=self.name, path=pf.rel, line=node.lineno,
                        col=node.col_offset,
                        message=msg + f" (in jit-reachable "
                                      f"`{fi.qualname}`)"))
        _for_each_function(ctx, visit)
        return out


@register
class NoTracerBranch(Rule):
    name = "no-tracer-branch"
    description = ("Python if/while/assert on a traced value inside "
                   "jit-reachable code; use lax.cond/lax.while_loop/"
                   "jnp.where")

    def check(self, ctx: LintContext) -> List[Finding]:
        out: List[Finding] = []

        def visit(fi, walker):
            pf = fi.module.pf
            for node in cached_walk(fi.node):
                kind = None
                test = None
                if isinstance(node, ast.If):
                    kind, test = "if", node.test
                elif isinstance(node, ast.While):
                    kind, test = "while", node.test
                elif isinstance(node, ast.Assert):
                    kind, test = "assert", node.test
                elif isinstance(node, ast.IfExp):
                    kind, test = "ternary", node.test
                if kind is None or not walker.taint(test):
                    continue
                out.append(Finding(
                    rule=self.name, path=pf.rel, line=node.lineno,
                    col=node.col_offset,
                    message=f"Python {kind} on a traced value in "
                            f"jit-reachable `{fi.qualname}` — use "
                            "lax.cond/lax.while_loop/jnp.where (or mark "
                            "the argument static)"))
        _for_each_function(ctx, visit)
        return out
