"""rng-stream-discipline: the static half of the byte-exact-resume
contract.

PR 1/PR 8 byte-exactness rests on one invariant: every random draw is a
pure function of (seed, absolute iteration, site) — `PRNGKey(seed +
abs_iter)`, `fold_in(key, tag)`, per-instance `RandomState`s carried in
the checkpoint.  Three statically-checkable ways to break it:

* **key reuse** — the same key VALUE consumed by two sampling ops
  (`normal(key, ...)` then `uniform(key, ...)`) yields correlated
  draws; jax keys are consumed exactly once, with `split`/`fold_in` as
  the only sanctioned derivations.  Tracked per function in statement
  order: consuming ops are the `jax.random` samplers AND `split`
  (splitting an already-consumed key is reuse too); `fold_in` derives
  without consuming (the package's tag-stream idiom); rebinding the
  name resets it.  A consumer inside a loop whose key is never rebound
  in that loop repeats the stream every iteration and is flagged on the
  same logic (the loop body is analyzed twice).

* **module-level numpy state** — `np.random.seed/rand/shuffle/...`
  mutate one hidden process-global stream: any other consumer (another
  subsystem, a retry, a different rank count) shifts every draw after
  it, and resume cannot reproduce it.  Instance RNGs
  (`np.random.RandomState(seed)`, `default_rng`) are the clean form and
  pass.

* **loop-invariant stream construction** — `PRNGKey(seed)` /
  `RandomState(seed)` built INSIDE a loop from arguments that never
  change across iterations re-seeds the identical stream every pass;
  the construction must be keyed by the loop variable or an absolute
  iteration (`PRNGKey(seed + abs_iter)` — the gbdt.py bagging idiom).

File-local by design (no call graph): key values that cross function
boundaries are not tracked — the fixtures pin the contract.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..callgraph import cached_walk
from ..core import Finding, LintContext, Rule, register

# jax.random ops that CONSUME their key argument (first positional or
# key=).  split consumes; fold_in derives a child stream and is the
# sanctioned way to reuse a parent key across tags.
_CONSUMERS = {
    "uniform", "normal", "bernoulli", "randint", "choice", "permutation",
    "shuffle", "gumbel", "exponential", "gamma", "beta", "poisson",
    "truncated_normal", "categorical", "laplace", "logistic",
    "rademacher", "bits", "ball", "dirichlet", "multivariate_normal",
    "orthogonal", "t", "cauchy", "double_sided_maxwell", "maxwell",
    "pareto", "rayleigh", "weibull_min", "loggamma", "binomial",
    "split",
}
_NP_INSTANCE_OK = {"RandomState", "default_rng", "Generator",
                   "SeedSequence", "BitGenerator", "PCG64", "Philox"}
_STREAM_CTORS = {"PRNGKey", "key", "RandomState", "default_rng"}


def _dotted_tail(mi, call: ast.Call):
    dotted = mi.dotted_of(call.func) or ""
    mod, _, tail = dotted.rpartition(".")
    return dotted, mod, tail


def _key_name(call: ast.Call) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == "key" and isinstance(kw.value, ast.Name):
            return kw.value.id
    if call.args and isinstance(call.args[0], ast.Name):
        return call.args[0].id
    return None


def _assigned_names(stmts: List[ast.AST]) -> Set[str]:
    out: Set[str] = set()
    for s in stmts:
        for n in cached_walk(s):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                out.add(n.id)
    return out


@register
class RngStreamDiscipline(Rule):
    name = "rng-stream-discipline"
    description = ("PRNG key reuse without split/fold_in, np.random "
                   "module-level state, or loop-invariant stream "
                   "construction — the byte-exact-resume RNG contract")
    file_local = True

    def check_file(self, ctx: LintContext, pf) -> List[Finding]:
        out: List[Finding] = []
        if pf.tree is None:
            return out
        from ..callgraph import module_info_for
        mi = module_info_for(ctx, pf)
        self._np_module_state(mi, pf, out)
        # one statement-ordered pass per function scope (module level too)
        scopes = [pf.tree] + [
            n for n in cached_walk(pf.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for scope in scopes:
            body = scope.body if not isinstance(scope, ast.Module) \
                else scope.body
            self._walk_block(mi, pf, body, set(), out, set(),
                             own_scope=scope)
        return out

    # ---- np.random module-level state ---------------------------------
    def _np_module_state(self, mi, pf, out: List[Finding]) -> None:
        for node in cached_walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted, mod, tail = _dotted_tail(mi, node)
            if mod in ("numpy.random", "np.random") \
                    and tail not in _NP_INSTANCE_OK:
                out.append(Finding(
                    rule=self.name, path=pf.rel, line=node.lineno,
                    col=node.col_offset,
                    message=f"np.random.{tail} uses the process-global "
                            "numpy stream: any other consumer (retry, "
                            "resume, rank-count change) shifts every "
                            "later draw — use an instance "
                            "RandomState/default_rng keyed by seed and "
                            "absolute iteration"))

    # ---- key-reuse + loop-invariant construction ----------------------
    def _walk_block(self, mi, pf, stmts: List[ast.AST],
                    consumed: Set[str], out: List[Finding],
                    reported: Set[int], own_scope=None,
                    loop_bound: Optional[Set[str]] = None) -> Set[str]:
        """Statement-ordered scan of one block; returns the consumed set
        at block exit.  `loop_bound`: names rebound per iteration of the
        innermost enclosing loop (None outside loops)."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scope, scanned on its own
            if isinstance(stmt, (ast.For, ast.While)):
                bound = _assigned_names([stmt])
                if isinstance(stmt, ast.For):
                    bound |= _assigned_names([stmt.target])
                self._loop_invariant_ctors(mi, pf, stmt, bound, out,
                                           reported)
                # analyze the body twice: the second pass sees the
                # first iteration's consumptions, catching a key
                # consumed on every pass without a per-iteration rebind
                inner = set(consumed)
                inner = self._walk_block(mi, pf, stmt.body, inner, out,
                                         set(), loop_bound=bound)
                self._walk_block(mi, pf, stmt.body, inner, out,
                                 reported, loop_bound=bound)
                consumed |= inner
                self._walk_block(mi, pf, stmt.orelse, consumed, out,
                                 reported, loop_bound=loop_bound)
                continue
            if isinstance(stmt, ast.If):
                a = self._walk_block(mi, pf, stmt.body, set(consumed),
                                     out, reported, loop_bound=loop_bound)
                b = self._walk_block(mi, pf, stmt.orelse, set(consumed),
                                     out, reported, loop_bound=loop_bound)
                consumed.clear()
                consumed |= a | b  # conservative merge
                continue
            if isinstance(stmt, (ast.With, ast.Try)):
                blocks = [getattr(stmt, "body", [])]
                for h in getattr(stmt, "handlers", []):
                    blocks.append(h.body)
                blocks.append(getattr(stmt, "orelse", []))
                blocks.append(getattr(stmt, "finalbody", []))
                for b in blocks:
                    consumed = self._walk_block(
                        mi, pf, b, consumed, out, reported,
                        loop_bound=loop_bound)
                continue
            # plain statement: consumptions first, then rebinds
            for node in cached_walk(stmt):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if not isinstance(node, ast.Call):
                    continue
                dotted, mod, tail = _dotted_tail(mi, node)
                if tail not in _CONSUMERS or not mod.endswith("random"):
                    continue
                key = _key_name(node)
                if key is None:
                    continue
                if key in consumed and id(node) not in reported:
                    reported.add(id(node))
                    out.append(Finding(
                        rule=self.name, path=pf.rel, line=node.lineno,
                        col=node.col_offset,
                        message=f"PRNG key `{key}` is consumed again by "
                                f"jax.random.{tail} without an "
                                "intervening split/fold_in rebind — "
                                "reused keys repeat the same draws"
                                + (" on every loop iteration"
                                   if loop_bound is not None
                                   and key not in loop_bound else "")
                                + ", breaking the draw-once stream "
                                "discipline byte-exact resume depends "
                                "on"))
                consumed.add(key)
            for n in cached_walk(stmt):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                    consumed.discard(n.id)
        return consumed

    def _loop_invariant_ctors(self, mi, pf, loop, bound: Set[str],
                              out: List[Finding],
                              reported: Set[int]) -> None:
        """`PRNGKey(seed)` / `RandomState(seed)` inside a loop with no
        argument depending on a name the loop rebinds."""
        for node in cached_walk(loop):
            if not isinstance(node, ast.Call) or id(node) in reported:
                continue
            dotted, mod, tail = _dotted_tail(mi, node)
            if tail not in _STREAM_CTORS:
                continue
            if not (mod.endswith("random") or mod in ("jax.random",)):
                continue
            args = list(node.args) + [kw.value for kw in node.keywords]
            if not args:
                continue
            names = {n.id for a in args for n in cached_walk(a)
                     if isinstance(n, ast.Name)}
            if names & bound:
                continue
            reported.add(id(node))
            out.append(Finding(
                rule=self.name, path=pf.rel, line=node.lineno,
                col=node.col_offset,
                message=f"{tail}(...) constructed inside a loop from "
                        "loop-invariant arguments: every iteration "
                        "re-seeds the identical stream — key the seed "
                        "by the loop/absolute iteration "
                        "(`PRNGKey(seed + abs_iter)`, the bagging "
                        "idiom) or hoist the construction out"))
