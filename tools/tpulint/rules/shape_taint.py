"""no-dynamic-shape-in-jit: data-dependent shapes inside jit scope.

XLA programs have static shapes: an op whose OUTPUT shape depends on
the VALUES of a traced array either fails to trace
(`jnp.nonzero(mask)` raises ConcretizationTypeError) or — the silent
form — forces a fresh compile for every distinct value when the shape
rides a Python scalar argument.  These are the recompile generators
PR 2's RecompileDetector only catches at runtime, after the multi-
second stall already happened.  This rule flags them at lint time,
over the same jit-reachable call graph + parameter taint the host-sync
rules use (callgraph.py, v2: methods and dispatch tables included).

Flagged (all only when the offending value is TRACED):

* `jnp.nonzero` / `flatnonzero` / `argwhere` / `unique*` without a
  `size=` keyword — the output length is data-dependent; jax requires
  `size=` (+ `fill_value`) inside jit;
* one-argument `jnp.where(mask)` — same contract as nonzero; the
  three-argument `jnp.where(mask, a, b)` select is the static-shape
  form and stays clean;
* boolean-mask indexing `x[mask]` — the canonical silent one: works
  in eager NumPy, dies under jit.  Masks are recognized syntactically
  (a comparison, a logical op, `isnan`/`isfinite`-family calls, or a
  name assigned from one);
* `jnp.repeat` / `.repeat()` with a traced repeats argument and no
  `total_repeat_length=`;
* a traced SHAPE argument to `reshape` / `zeros` / `ones` / `full` /
  `empty` / `arange` / `broadcast_to` / `tile` / `eye` / `linspace` —
  shapes must be Python values at trace time; deriving one from a
  traced array is a trace error, and deriving it from a non-static
  Python parameter recompiles per distinct value (mark the parameter
  `static_argnames` if it is configuration).

`x.reshape(-1)` and friends on static geometry stay clean: `.shape`
access is a static value in the taint model, and constants never
taint.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..core import Finding, LintContext, Rule, register
from ..callgraph import cached_walk
from .host_sync import _for_each_function

_NP_MODULES = ("jax.numpy", "jnp", "numpy", "np")

# value -> data-dependent output length unless size= is given
DYN_LEN_FUNCS = {"nonzero", "flatnonzero", "argwhere", "unique",
                 "unique_all", "unique_counts", "unique_inverse",
                 "unique_values"}

# constructor/reshape family: which call arguments carry a shape
# (None = every positional argument, e.g. arange's start/stop/step)
SHAPE_ARG_FUNCS = {
    "reshape": [1], "zeros": [0], "ones": [0], "empty": [0],
    "full": [0], "arange": None, "broadcast_to": [1], "tile": [1],
    "eye": [0, 1], "linspace": [2],
}

# calls whose result is a boolean mask
_BOOL_CALLS = {"isnan", "isfinite", "isinf", "isneginf", "isposinf",
               "logical_and", "logical_or", "logical_not", "logical_xor",
               "greater", "greater_equal", "less", "less_equal",
               "equal", "not_equal", "isin", "isclose"}


def _np_func(mi, call: ast.Call) -> Optional[str]:
    dotted = mi.dotted_of(call.func) or ""
    parts = dotted.rsplit(".", 1)
    if len(parts) == 2 and parts[0] in _NP_MODULES:
        return parts[1]
    return None


def _has_kw(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


class _BoolNames:
    """Names assigned from boolean-mask expressions, keyed by their
    owning lexical scope (two unrelated `pos` bindings in different
    nested functions stay distinct).  Not flow-sensitive — a linter
    approximation pinned by the fixture tests."""

    def __init__(self, mi, walker):
        self.mi = mi
        self.walker = walker
        self.keys: Set[tuple] = set()
        for _ in range(4):
            before = len(self.keys)
            for node in cached_walk(walker.fi.node):
                if isinstance(node, ast.Assign) \
                        and self.is_bool_expr(node.value):
                    scope = walker.node_scope.get(id(node))
                    if scope is None:
                        continue
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            owner = scope.owner_of(t.id) or scope
                            self.keys.add((id(owner), t.id))
            if len(self.keys) == before:
                break

    def _name_is_bool(self, e: ast.Name) -> bool:
        scope = self.walker.node_scope.get(id(e))
        if scope is None:
            return False
        owner = scope.owner_of(e.id)
        return owner is not None and (id(owner), e.id) in self.keys

    def is_bool_expr(self, e: Optional[ast.AST]) -> bool:
        if e is None:
            return False
        if isinstance(e, ast.Compare):
            return not all(isinstance(op, (ast.Is, ast.IsNot))
                           for op in e.ops)
        if isinstance(e, ast.BoolOp):
            return any(self.is_bool_expr(v) for v in e.values)
        if isinstance(e, ast.UnaryOp):
            return isinstance(e.op, (ast.Invert, ast.Not)) \
                and self.is_bool_expr(e.operand)
        if isinstance(e, ast.BinOp) and isinstance(e.op, (ast.BitAnd,
                                                          ast.BitOr,
                                                          ast.BitXor)):
            return self.is_bool_expr(e.left) or self.is_bool_expr(e.right)
        if isinstance(e, ast.Name):
            return self._name_is_bool(e)
        if isinstance(e, ast.Call):
            fn = _np_func(self.mi, e)
            if fn in _BOOL_CALLS:
                return True
            if isinstance(e.func, ast.Attribute) \
                    and e.func.attr == "astype" and e.args:
                a0 = e.args[0]
                return (isinstance(a0, ast.Name) and a0.id == "bool") \
                    or (isinstance(a0, ast.Constant) and a0.value == "bool")
        return False


@register
class NoDynamicShapeInJit(Rule):
    name = "no-dynamic-shape-in-jit"
    description = ("data-dependent output shape inside jit-reachable "
                   "code (nonzero/unique/1-arg where without size=, "
                   "boolean-mask indexing, traced shape arguments) — a "
                   "trace error or a silent recompile per value")

    def check(self, ctx: LintContext) -> List[Finding]:
        out: List[Finding] = []

        def flag(pf, node, fi, msg):
            out.append(Finding(
                rule=self.name, path=pf.rel, line=node.lineno,
                col=node.col_offset,
                message=msg + f" (in jit-reachable `{fi.qualname}`)"))

        def visit(fi, walker):
            pf = fi.module.pf
            mi = fi.module
            bools = _BoolNames(mi, walker)
            for node in cached_walk(fi.node):
                if isinstance(node, ast.Call):
                    self._check_call(pf, mi, node, fi, walker, flag)
                elif isinstance(node, ast.Subscript):
                    self._check_mask_index(pf, node, fi, walker, bools,
                                           flag)

        _for_each_function(ctx, visit)
        return out

    # ---- calls --------------------------------------------------------
    def _check_call(self, pf, mi, node: ast.Call, fi, walker, flag):
        fn = _np_func(mi, node)
        args = list(node.args)
        if fn in DYN_LEN_FUNCS:
            if args and walker.taint(args[0]) and not _has_kw(node,
                                                              "size"):
                flag(pf, node, fi,
                     f"jnp.{fn} on a traced value without size= has a "
                     "data-dependent output shape — pass size= (and "
                     "fill_value=) or restructure with a mask")
            return
        if fn == "where":
            if len(args) == 1 and not node.keywords \
                    and walker.taint(args[0]):
                flag(pf, node, fi,
                     "one-argument jnp.where on a traced mask has a "
                     "data-dependent output shape — use the three-"
                     "argument jnp.where(mask, a, b) or pass size=")
            return
        if fn == "repeat" or (isinstance(node.func, ast.Attribute)
                              and node.func.attr == "repeat"):
            reps = None
            if fn == "repeat" and len(args) >= 2:
                reps = args[1]
            elif fn is None and args:  # method form x.repeat(r)
                reps = args[0]
            for kw in node.keywords:
                if kw.arg == "repeats":
                    reps = kw.value
            if reps is not None and walker.taint(reps) \
                    and not _has_kw(node, "total_repeat_length"):
                flag(pf, node, fi,
                     "repeat with traced repeats has a data-dependent "
                     "output shape — pass total_repeat_length= or make "
                     "the repeats static")
            return
        # traced shape arguments (module functions and .reshape method)
        shape_args: List[ast.AST] = []
        if fn in SHAPE_ARG_FUNCS:
            idxs = SHAPE_ARG_FUNCS[fn]
            shape_args = args if idxs is None else [
                args[i] for i in idxs if i < len(args)]
            shape_args += [kw.value for kw in node.keywords
                           if kw.arg in ("shape", "num")]
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr == "reshape":
            shape_args = args
        for sa in shape_args:
            if walker.taint(sa):
                flag(pf, node, fi,
                     "traced value used as a shape argument — shapes "
                     "are static under jit: derive it from .shape, or "
                     "mark the parameter static_argnames if it is "
                     "configuration (a Python scalar here recompiles "
                     "per distinct value)")
                break

    # ---- boolean-mask indexing ---------------------------------------
    def _check_mask_index(self, pf, node: ast.Subscript, fi, walker,
                          bools: _BoolNames, flag):
        idx = node.slice
        cands = list(idx.elts) if isinstance(idx, ast.Tuple) else [idx]
        for c in cands:
            if bools.is_bool_expr(c) and walker.taint(c):
                flag(pf, node, fi,
                     "boolean-mask indexing on a traced mask has a "
                     "data-dependent output shape — use "
                     "jnp.where(mask, a, b) or masked reductions")
                return
