"""signal-handler-safety: no unbounded blocking on the process's last
breath.

A SIGTERM handler (the preemption notice) and the stall watchdog's exit
path both run when the rest of the process may already be wedged — the
AsyncWriter worker stuck on a dead disk, the main thread parked inside a
collective.  Any UNBOUNDED wait on that path turns a recoverable
preemption into the r05 shape: a live process that never exits and never
explains itself.  PR 7 learned this by hand for the stall-file writer
("synchronously, never via the possibly-hung AsyncWriter"); this rule
enforces it mechanically.

Roots (callgraph v3 `concurrency_roots`):

* **signal handlers** — callables registered via `signal.signal(sig,
  fn)` (incl. nested closures) and callable arguments of
  `faulthandler.register`;
* **watchdog exit paths** — functions reachable from a thread entry
  point (`threading.Thread(target=...)` or a `.submit(...)`-deferred
  callable) that call `os._exit`: a thread that ends the process is by
  definition running while something else is broken.

The reachable set is walked with the v2 call graph plus a DUCK-TYPED
fallback: a method call on an untypeable receiver (`_current.emit(...)`,
`w.flush(...)`) resolves to every in-package method of that name.
Over-approximating reach is the correct bias for a safety rule — the
cost of a false edge is one justified suppression, the cost of a missed
edge is a hung preemption.

Flagged inside the reachable set:

* `<queue>.put(...)` without `timeout=`/`block=False` — blocks forever
  when the queue is full and its worker is wedged (the exact PR-7/8
  hazard: the terminal `sigterm`/`stall` event routed through the
  AsyncWriter's bounded queue);
* `<queue>.join()` / `<queue>.get()` without a bound;
* `<lock>.acquire()` without `timeout=`/`blocking=False`, and
  `with <lock>:` — a handler interrupting the thread that HOLDS the
  lock deadlocks on it (non-reentrancy);
* `<event>.wait()` / `<thread>.join()` without a timeout;
* jax dispatch (`jax.*` / `jnp.*` calls) — device interaction from a
  handler can block on a wedged runtime and reenters a client that is
  not async-signal-safe.

Calls that carry a bound (`timeout=`, `block=False`, `blocking=False`)
pass.  Not modeled (documented approximations): the run-scoped
preemption hook installed via `set_preemption_hook` (a module-global
function pointer the graph cannot follow — its jax dispatch is an
accepted, grace-bounded exception by design), and `if timeout is None`
guards around an unbounded branch that callers never take (suppress
with the justification saying so).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from ..callgraph import cached_walk
from ..core import Finding, LintContext, Rule, register
from ._concur import has_bound, local_ctor_types, receiver_kind
from .host_sync import _analyze


def _contains_os_exit(mi, fn_node: ast.AST) -> bool:
    for node in cached_walk(fn_node):
        if isinstance(node, ast.Call) \
                and (mi.dotted_of(node.func) or "") == "os._exit":
            return True
    return False


def concurrency_reaches(ctx: LintContext):
    """(handler_reach, exit_reach) — {id(fi): fi} closures, cached on
    ctx, shared with thread-shared-state."""
    cached = getattr(ctx, "_tpulint_concur_reach", None)
    if cached is None:
        index, _ = _analyze(ctx)
        handler_roots, thread_roots = index.concurrency_roots()
        handler_reach = index.reachable_from(handler_roots, duck=True)
        thread_reach = index.reachable_from(thread_roots, duck=False)
        exit_roots = [fi for fi in thread_reach.values()
                      if fi.node is not None
                      and _contains_os_exit(fi.module, fi.node)]
        exit_reach = index.reachable_from(exit_roots, duck=True)
        cached = (index, handler_reach, exit_reach, thread_reach)
        ctx._tpulint_concur_reach = cached  # type: ignore[attr-defined]
    return cached


@register
class SignalHandlerSafety(Rule):
    name = "signal-handler-safety"
    description = ("unbounded blocking (queue put/join, lock acquire, "
                   "event wait) or jax dispatch reachable from a signal "
                   "handler or a watchdog exit path")

    def check(self, ctx: LintContext) -> List[Finding]:
        _, handler_reach, exit_reach, _ = concurrency_reaches(ctx)
        out: List[Finding] = []
        seen: set = set()
        for reach, ctx_name in ((handler_reach, "a signal handler"),
                                (exit_reach, "a watchdog exit path")):
            for fi in reach.values():
                if fi.node is None or id(fi.node) in seen:
                    continue
                seen.add(id(fi.node))
                self._scan(fi, ctx_name, out)
        return out

    def _scan(self, fi, ctx_name: str, out: List[Finding]) -> None:
        mi, owner = fi.module, fi.owner_class
        pf = mi.pf
        locals_ = local_ctor_types(mi, fi.node)

        def emit(node, msg):
            out.append(Finding(
                rule=self.name, path=pf.rel, line=node.lineno,
                col=node.col_offset,
                message=f"{msg} — reachable from {ctx_name} via "
                        f"`{fi.qualname}`; the rest of the process may "
                        "already be wedged, so every wait here must be "
                        "bounded (docs/StaticAnalysis.md)"))

        for node in cached_walk(fi.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    kind = receiver_kind(mi, owner, locals_,
                                         item.context_expr)
                    if kind == "lock":
                        emit(item.context_expr,
                             "`with <lock>:` acquires a lock with no "
                             "timeout; a handler interrupting the "
                             "holder deadlocks (non-reentrant)")
                continue
            if not isinstance(node, ast.Call):
                continue
            dotted = mi.dotted_of(node.func) or ""
            if dotted.startswith(("jax.", "jnp.")) \
                    or dotted.split(".", 1)[0] in ("jax", "jnp"):
                emit(node, f"`{dotted}` dispatches to the device runtime"
                           ", which may itself be wedged during a "
                           "stall/preemption")
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            meth = node.func.attr
            kind = receiver_kind(mi, owner, locals_, node.func.value)
            if meth == "put" and kind == "queue" \
                    and not has_bound(node):
                emit(node, "blocking queue put with no timeout: blocks "
                           "forever when the queue is full and its "
                           "worker is hung (write synchronously here "
                           "instead — the PR-7 stall-writer rule)")
            elif meth == "join" and kind in ("queue", "thread") \
                    and not has_bound(node) and not node.args:
                emit(node, f"unbounded {kind} join")
            elif meth == "get" and kind == "queue" \
                    and not has_bound(node):
                emit(node, "blocking queue get with no timeout")
            elif meth == "acquire" and kind == "lock" \
                    and not has_bound(node):
                emit(node, "lock acquire with no timeout (non-reentrant "
                           "deadlock if the interrupted thread holds it)")
            elif meth == "wait" and kind in ("event", "lock") \
                    and not has_bound(node) and not node.args:
                emit(node, "unbounded wait")
