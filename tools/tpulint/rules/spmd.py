"""spmd-axis-discipline: mesh-axis and shard_map hygiene.

Under SPMD three classes of mistake produce deadlocks, wrong numbers,
or the r05-style multi-device stall — none of which a single-device
test can see:

* a collective naming an axis the mesh does not declare fails at run
  time only on a real multi-device mesh (`unbound axis name`), i.e. in
  the expensive environment;
* a collective OUTSIDE any `shard_map`-wrapped body traces fine on one
  device (axis size 1) and deadlocks or mis-reduces under GSPMD when
  ranks disagree about program order;
(The sibling `donated-sharding` rule covers the third hazard of the
family: donating into a shard_map'd entry without explicit
`in_shardings`.)

Checks (package-wide, AST + the v2 call graph):

1. **axis registry**: every `Mesh(..., (<axes>,))` construction in the
   package declares its axis names (string literals, or names bound to
   module-level string constants — `DATA_AXIS = "data"`).
2. **axis names**: literal axis arguments of `lax.psum`/`pmean`/...
   and string entries of `PartitionSpec(...)` specs must be declared
   axes.  Non-literal axes (a parameter like `params.data_axis`) are
   runtime configuration and are not checked.
3. **shard_map containment**: a collective must live in a function
   lexically inside, or reachable through the call graph from, a
   function passed to `shard_map` (the wave engine's `_psum` sits two
   modules away from its `shard_map` wrapper — the v2 graph closes
   that distance).  `distributed.py` is exempt: its collectives ride
   the multi-process `jax.experimental` runtime, not a shard_map.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..core import Finding, LintContext, Rule, register
from ..callgraph import cached_walk
from .host_sync import _analyze

COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
               "psum_scatter", "all_to_all", "ppermute", "pshuffle",
               "axis_index"}
_EXEMPT_FILES = {"distributed.py"}


def _str_const(mi, expr: ast.AST) -> Optional[str]:
    """A string literal, or a Name bound to a module-level string."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.Name):
        for e in mi.binding_exprs.get(expr.id, []):
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                return e.value
    return None


def _axis_strs(mi, expr: ast.AST) -> List[str]:
    out = []
    if isinstance(expr, (ast.Tuple, ast.List)):
        elts = expr.elts
    else:
        elts = [expr]
    for e in elts:
        s = _str_const(mi, e)
        if s is not None:
            out.append(s)
    return out


def _is_shard_map_call(mi, expr: ast.AST) -> bool:
    return (isinstance(expr, ast.Call)
            and (mi.dotted_of(expr.func) or "").rsplit(".", 1)[-1]
            == "shard_map")


@register
class SpmdAxisDiscipline(Rule):
    name = "spmd-axis-discipline"
    description = ("collective/PartitionSpec axis names must match the "
                   "declared mesh axes, and collectives must live inside "
                   "(or be reachable from) shard_map-wrapped bodies")

    def check(self, ctx: LintContext) -> List[Finding]:
        index, _ = _analyze(ctx)
        out: List[Finding] = []
        axes = self._declared_axes(index)
        rooted = self._shard_map_rooted(index)
        for mi in index.modules.values():
            if mi.pf.tree is None:
                continue
            self._check_module(mi, index, axes, rooted, out)
        return out

    # ---- 1. axis registry ---------------------------------------------
    def _declared_axes(self, index) -> Set[str]:
        axes: Set[str] = set()
        for mi in index.modules.values():
            if mi.pf.tree is None:
                continue
            for node in cached_walk(mi.pf.tree):
                if not isinstance(node, ast.Call):
                    continue
                dotted = (mi.dotted_of(node.func) or "").rsplit(".", 1)[-1]
                if dotted != "Mesh":
                    continue
                cand = None
                if len(node.args) >= 2:
                    cand = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        cand = kw.value
                if cand is not None:
                    axes.update(_axis_strs(mi, cand))
        return axes

    # ---- 3. shard_map reachability ------------------------------------
    def _shard_map_rooted(self, index) -> Set[int]:
        """ids of def nodes lexically passed to shard_map, plus
        everything reachable from them through the call graph."""
        rooted_funcs = []  # FuncInfo seeds
        rooted_defs: Set[int] = set()

        def note_ref(mi, owner, encl_nested, expr):
            if isinstance(expr, ast.Name) and expr.id in encl_nested:
                # nested def passed to shard_map: rooted, and its own
                # callees must be expanded too (the wave engine's _psum
                # sits behind inner -> grow_tree_wave_impl)
                rooted_funcs.append(index._func_for_def(
                    mi, encl_nested[expr.id]))
                return
            for fid in index.collect_refs(mi, expr, owner, None):
                rooted_funcs.append(index.func(fid))

        for mi in index.modules.values():
            if mi.pf.tree is None:
                continue
            funcs = list(mi.top_funcs.values())
            for ci in mi.top_classes.values():
                funcs += list(ci.methods.values())
            for fi in funcs:
                if isinstance(fi.node, ast.Lambda):
                    continue
                nested = {n.name: n for n in cached_walk(fi.node)
                          if isinstance(n, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))
                          and n is not fi.node}
                for node in cached_walk(fi.node):
                    if _is_shard_map_call(mi, node):
                        target = node.args[0] if node.args else None
                        for kw in node.keywords:
                            if kw.arg in ("f", "fun"):
                                target = kw.value
                        if target is not None:
                            note_ref(mi, fi.owner_class, nested, target)
            # module-level shard_map calls
            for node in cached_walk(mi.pf.tree):
                if _is_shard_map_call(mi, node) and node.args:
                    note_ref(mi, None, {}, node.args[0])

        # BFS over the call graph from the rooted functions
        seen: Set[int] = set()
        work = list(rooted_funcs)
        while work:
            fi = work.pop()
            if id(fi) in seen or fi.node is None:
                continue
            seen.add(id(fi))
            rooted_defs.add(id(fi.node))
            for node in cached_walk(fi.node):
                if isinstance(node, ast.Call):
                    for callee, _off in index.resolve_call_multi(
                            fi.module, node.func, fi.owner_class):
                        work.append(callee)
        return rooted_defs

    # ---- per-module checks --------------------------------------------
    def _check_module(self, mi, index, axes: Set[str],
                      rooted: Set[int], out: List[Finding]) -> None:
        def enclosing_defs(target: ast.AST) -> List[ast.AST]:
            # nearest enclosing def of an arbitrary node
            found: List[ast.AST] = []

            def rec(node, chain):
                if node is target:
                    found.extend(chain)
                    return True
                for child in ast.iter_child_nodes(node):
                    nxt = chain + [child] if isinstance(
                        child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)) else chain
                    if rec(child, nxt):
                        return True
                return False

            rec(mi.pf.tree, [])
            return found

        exempt = mi.pf.pkg_rel in _EXEMPT_FILES
        for node in cached_walk(mi.pf.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = mi.dotted_of(node.func) or ""
            mod, _, tail = dotted.rpartition(".")
            # 2. collective axis names + 3. shard_map containment
            if tail in COLLECTIVES and mod in ("jax.lax", "lax"):
                axis_expr = None
                if len(node.args) >= 2:
                    axis_expr = node.args[1]
                elif tail == "axis_index" and node.args:
                    axis_expr = node.args[0]
                for kw in node.keywords:
                    if kw.arg in ("axis_name", "axis"):
                        axis_expr = kw.value
                if axis_expr is not None and axes:
                    for s in _axis_strs(mi, axis_expr):
                        if s not in axes:
                            out.append(Finding(
                                rule=self.name, path=mi.pf.rel,
                                line=node.lineno, col=node.col_offset,
                                message=f"lax.{tail} names axis {s!r}, "
                                        "which no Mesh in the package "
                                        "declares (declared: "
                                        f"{sorted(axes)}) — an unbound "
                                        "axis fails only on the real "
                                        "multi-device mesh"))
                if not exempt:
                    chain = enclosing_defs(node)
                    if not any(id(d) in rooted for d in chain):
                        out.append(Finding(
                            rule=self.name, path=mi.pf.rel,
                            line=node.lineno, col=node.col_offset,
                            message=f"lax.{tail} outside any shard_map-"
                                    "wrapped body (lexically or via the "
                                    "call graph) — under GSPMD an "
                                    "unmapped collective deadlocks or "
                                    "mis-reduces when ranks disagree "
                                    "about program order"))
            # 2b. PartitionSpec axis strings
            elif tail in ("PartitionSpec", "P") and axes \
                    and mod.startswith(("jax", "")):
                for a in list(node.args) + [kw.value
                                            for kw in node.keywords]:
                    s = _str_const(mi, a)
                    if s is not None and s not in axes:
                        out.append(Finding(
                            rule=self.name, path=mi.pf.rel,
                            line=node.lineno, col=node.col_offset,
                            message=f"PartitionSpec names axis {s!r}, "
                                    "which no Mesh in the package "
                                    f"declares (declared: {sorted(axes)})"))
