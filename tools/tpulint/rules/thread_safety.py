"""thread-shared-state: a lockset-style race detector for the host-side
concurrency surface.

PRs 7–8 grew real threads — the RunGuard watchdog, the AsyncWriter
worker, submit()-deferred checkpoint writes — and the bug class that
produces the next silent failure is an attribute mutated on one thread
while another reads it with no synchronization (RunGuard's tick state
vs. the watchdog, the checkpoint generations list vs. save_now).  None
of that is visible to a single-threaded test.

Model (docs/StaticAnalysis.md "The lockset model"):

* every function is assigned to one or more CONCURRENT ROOT SETS —
  *thread* (reachable from a `threading.Thread(target=...)` entry or a
  `.submit(...)`-deferred callable), *handler* (reachable from a signal
  handler, duck-typed reach), and *main* (reachable from everything
  else);
* accesses to `self.<attr>` inside a class's methods are collected with
  the set of locks lexically held (`with self._lock:` /
  `with lock:` blocks; lock-ness per `_concur` typing);
* an attribute WRITTEN outside `__init__` in one root set and accessed
  in a different root set with an empty lockset intersection is a
  finding, reported at the unlocked site.  A function that belongs to
  both the *thread* set and another set races WITH ITSELF, so two
  distinct access sites inside the thread-shared function pair conflict
  too.
* module GLOBALS rebound under a `global` declaration get the same
  treatment across the functions of their module.

Happens-before exemptions: writes in `__init__`, and writes in the
method that CONSTRUCTS the thread when the conflicting access is on the
constructed thread's side (`Thread.start()` publishes everything
sequenced before it).

Known approximations (pinned by the fixtures): mutating METHOD calls
(`self.knobs.update(...)`, `deque.append`) are not writes — CPython
makes single bytecode container ops atomic, and counting them floods
the rule; locks held by a CALLER are invisible at the callee's accesses
(hold the lock lexically around the access, or restructure); closure
dicts shared with a handler (`_progress` in engine.train) are untyped
and unseen.  The same-thread `handler` set never conflicts with itself
within one function (reentrancy, not a data race).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..callgraph import cached_walk
from ..core import Finding, LintContext, Rule, register
from ._concur import kind_of_ctor, local_ctor_types, lock_token, \
    receiver_kind
from .host_sync import _analyze
from .signal_safety import concurrency_reaches

# sync primitives are internally consistent; rebinding a Thread attr is
# still interesting (the flush-reads-_thread shape), so 'thread' stays
_EXEMPT_ATTR_KINDS = {"lock", "queue", "event"}


@dataclass
class _Access:
    attr: str
    is_write: bool
    func: object                 # FuncInfo
    node: ast.AST
    locks: FrozenSet[str]
    sides: FrozenSet[str] = frozenset()
    in_init: bool = False
    prestart: bool = False       # write in the thread-creating method


def _is_thread_ctor(mi, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dotted = mi.dotted_of(node.func) or ""
    return dotted.rsplit(".", 1)[-1] == "Thread" \
        and dotted.startswith(("threading.", "Thread"))


class _AccessCollector:
    """Lexically-scoped walk of one function body collecting self.<attr>
    reads/writes and `global` rebinds, with the held lockset."""

    def __init__(self, mi, owner, fi):
        self.mi = mi
        self.owner = owner
        self.fi = fi
        self.locals_ = local_ctor_types(mi, fi.node)
        self.attr_accesses: List[_Access] = []
        self.global_writes: Dict[str, List[_Access]] = {}
        self.global_reads: Dict[str, List[_Access]] = {}
        self.global_names: Set[str] = set()
        self._claimed: Set[int] = set()
        for n in cached_walk(fi.node):
            if isinstance(n, ast.Global):
                self.global_names.update(n.names)
        self._visit(fi.node, frozenset(), in_nested=False)

    # ---- helpers ------------------------------------------------------
    def _self_attr(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id in ("self", "cls"):
            return expr.attr
        return None

    def _write_target_attrs(self, t: ast.AST) -> List[Tuple[str, ast.AST]]:
        out = []
        for n in cached_walk(t):
            if isinstance(n, ast.Subscript):
                attr = self._self_attr(n.value)
                if attr is not None:
                    out.append((attr, n.value))
                    self._claimed.add(id(n.value))
            else:
                attr = self._self_attr(n)
                if attr is not None and isinstance(
                        getattr(n, "ctx", None), ast.Store):
                    out.append((attr, n))
        return out

    def _record_attr(self, attr, node, is_write, locks):
        self.attr_accesses.append(_Access(
            attr=attr, is_write=is_write, func=self.fi, node=node,
            locks=locks))

    def _record_global(self, name, node, is_write, locks):
        table = self.global_writes if is_write else self.global_reads
        table.setdefault(name, []).append(_Access(
            attr=name, is_write=is_write, func=self.fi, node=node,
            locks=locks))

    # ---- walk ---------------------------------------------------------
    def _visit(self, node: ast.AST, locks: FrozenSet[str],
               in_nested: bool) -> None:
        if isinstance(node, ast.With):
            held = set(locks)
            for item in node.items:
                if receiver_kind(self.mi, self.owner, self.locals_,
                                 item.context_expr) == "lock":
                    tok = lock_token(item.context_expr)
                    if tok:
                        held.add(tok)
            for child in node.body:
                self._visit(child, frozenset(held), in_nested)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not self.fi.node:
            # a nested def runs later: the enclosing lockset is NOT held
            body = node.body if not isinstance(node, ast.Lambda) \
                else [node.body]
            for child in body:
                self._visit(child, frozenset(), True)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                for attr, tn in self._write_target_attrs(t):
                    self._record_attr(attr, tn, True, locks)
                for n in cached_walk(t):
                    if isinstance(n, ast.Name) \
                            and isinstance(n.ctx, ast.Store) \
                            and n.id in self.global_names:
                        self._record_global(n.id, n, True, locks)
            if node.value is not None:
                self._visit(node.value, locks, in_nested)
            return
        attr = self._self_attr(node)
        if attr is not None and id(node) not in self._claimed \
                and isinstance(getattr(node, "ctx", None), ast.Load):
            self._record_attr(attr, node, False, locks)
        if isinstance(node, ast.Name) \
                and isinstance(getattr(node, "ctx", None), ast.Load):
            self._record_global(node.id, node, False, locks)
        for child in ast.iter_child_nodes(node):
            self._visit(child, locks, in_nested)


@register
class ThreadSharedState(Rule):
    name = "thread-shared-state"
    description = ("attribute/global written on one concurrent root "
                   "(thread / signal handler / main) and accessed on "
                   "another with no common lock")

    def check(self, ctx: LintContext) -> List[Finding]:
        index, handler_reach, _exit_reach, thread_reach = \
            concurrency_reaches(ctx)
        # main set: closure of everything that is not already on a
        # concurrent root — a function can be in several sets
        main_seeds = [fi for fi in index._named_funcs()
                      if id(fi) not in thread_reach
                      and id(fi) not in handler_reach]
        main_reach = index.reachable_from(main_seeds, duck=False)

        def sides(fi) -> FrozenSet[str]:
            s = set()
            if id(fi) in thread_reach:
                s.add("thread")
            if id(fi) in handler_reach:
                s.add("handler")
            if id(fi) in main_reach:
                s.add("main")
            return frozenset(s or {"main"})

        out: List[Finding] = []
        for mi in index.modules.values():
            if mi.pf.tree is None:
                continue
            self._check_classes(index, mi, sides, out)
            self._check_globals(index, mi, sides, out)
        return out

    # ---- classes ------------------------------------------------------
    def _check_classes(self, index, mi, sides, out) -> None:
        for ci in mi.top_classes.values():
            methods = list(ci.methods.values())
            if not any("thread" in sides(m) or "handler" in sides(m)
                       for m in methods):
                continue  # no concurrency touches this class
            accesses: List[_Access] = []
            for m in methods:
                if m.node is None:
                    continue
                coll = _AccessCollector(mi, ci, m)
                s = sides(m)
                init = m.qualname.endswith("__init__")
                pre = any(_is_thread_ctor(mi, n)
                          for n in cached_walk(m.node))
                for a in coll.attr_accesses:
                    a.sides, a.in_init, a.prestart = s, init, pre
                    accesses.append(a)
            self._conflicts(ci.name, accesses, out,
                            attr_kind=lambda attr: kind_of_ctor(
                                ci.find_attr_type(attr)))

    # ---- globals ------------------------------------------------------
    def _check_globals(self, index, mi, sides, out) -> None:
        # cheap gate: a module with no `global` statement has no
        # function-scope global rebinds to analyze
        if not any(isinstance(n, ast.Global)
                   for n in cached_walk(mi.pf.tree)):
            return
        funcs = list(mi.top_funcs.values())
        for ci in mi.top_classes.values():
            funcs += list(ci.methods.values())
        writes: List[_Access] = []
        reads: Dict[str, List[_Access]] = {}
        written_names: Set[str] = set()
        colls = []
        for fi in funcs:
            if fi.node is None or isinstance(fi.node, ast.Lambda):
                continue
            coll = _AccessCollector(mi, fi.owner_class, fi)
            colls.append((fi, coll))
            for name, accs in coll.global_writes.items():
                written_names.add(name)
                for a in accs:
                    a.sides = sides(fi)
                    writes.append(a)
        if not written_names:
            return
        for fi, coll in colls:
            for name in written_names:
                for a in coll.global_reads.get(name, []):
                    a.sides = sides(fi)
                    reads.setdefault(name, []).append(a)
        accesses = writes + [a for accs in reads.values() for a in accs]
        self._conflicts(mi.dotted.rsplit(".", 1)[-1], accesses, out,
                        attr_kind=lambda attr: None, kind_word="global")

    # ---- conflict detection -------------------------------------------
    def _conflicts(self, scope_name: str, accesses: List[_Access], out,
                   attr_kind, kind_word: str = "attribute") -> None:
        by_attr: Dict[str, List[_Access]] = {}
        for a in accesses:
            by_attr.setdefault(a.attr, []).append(a)
        for attr, accs in sorted(by_attr.items()):
            if attr_kind(attr) in _EXEMPT_ATTR_KINDS:
                continue
            writes = [a for a in accs if a.is_write and not a.in_init]
            if not writes:
                continue
            found = None
            for w in writes:
                for a in accs:
                    if a is w or a.in_init:
                        continue
                    union = w.sides | a.sides
                    if len(union) < 2:
                        continue
                    if a.func is w.func:
                        # one function racing with itself needs real
                        # parallelism (a thread side), not reentrancy
                        common = w.sides & a.sides
                        if "thread" not in common or len(common) < 2:
                            continue
                    # Thread.start() publishes writes sequenced before
                    # it: the creator method's writes are safe against
                    # the created thread's side
                    if w.prestart and a.sides == frozenset({"thread"}):
                        continue
                    if w.locks & a.locks:
                        continue
                    found = (w, a)
                    break
                if found:
                    break
            if not found:
                continue
            w, a = found
            site = w if not w.locks else a
            other = a if site is w else w
            out.append(Finding(
                rule=self.name, path=site.func.module.pf.rel,
                line=site.node.lineno, col=site.node.col_offset,
                message=f"{kind_word} `{attr}` of `{scope_name}` is "
                        f"{'written' if site.is_write else 'read'} in "
                        f"`{site.func.qualname}` "
                        f"({'/'.join(sorted(site.sides))} side) with no "
                        f"lock while `{other.func.qualname}` "
                        f"({'/'.join(sorted(other.sides))} side) "
                        f"{'writes' if other.is_write else 'reads'} it"
                        " — hold one common lock at both sites or "
                        "confine the state to one thread "
                        "(docs/StaticAnalysis.md lockset model)"))
