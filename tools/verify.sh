#!/usr/bin/env bash
# Single merge gate (ISSUE 8): static analysis + config-doc sync + the
# elastic chaos drill + full tier-1 — one command, one exit code.
#
#   tools/verify.sh          # everything (tier-1 takes ~15 min on CPU)
#   tools/verify.sh --quick  # skip the full tier-1 (lint + docs + drill)
#
# The chaos drill (tests/test_elastic.py) runs FIRST and separately so a
# recovery-path regression is a named failure at the top of the output,
# not a dot lost somewhere inside the tier-1 stream.
set -uo pipefail
cd "$(dirname "$0")/.."

fail=0
step() { echo; echo "==== $* ===="; }

step "tpulint (baseline: no new findings)"
python -m tools.tpulint lightgbm_tpu --baseline .tpulint_baseline.json \
    || fail=1

step "tpulint suppression audit"
python -m tools.tpulint lightgbm_tpu --list-suppressions || fail=1

step "tpulint IR audit (--ir: jaxpr-level, docs/StaticAnalysis.md v4)"
ir_t0=$SECONDS
JAX_PLATFORMS=cpu python -m tools.tpulint lightgbm_tpu --ir \
    --baseline .tpulint_baseline.json || fail=1
echo "ir-audit wall: $((SECONDS - ir_t0))s (cold ~5 s / warm <1 s, vs ~2 s cold AST lint)"

step "config-doc sync (docs/Parameters.md)"
python tools/gen_params_doc.py --check || fail=1

step "event-doc sync (docs/Observability.md event table)"
python tools/check_event_docs.py || fail=1

step "fallback-matrix sync (docs/Inference.md host-fallback matrix)"
python tools/check_fallback_docs.py || fail=1

step "elastic chaos drill (tests/test_elastic.py)"
JAX_PLATFORMS=cpu python -m pytest tests/test_elastic.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly || fail=1

step "serving suite (tests/test_serving.py)"
JAX_PLATFORMS=cpu python -m pytest tests/test_serving.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly || fail=1

step "fleet suite (tests/test_fleet.py)"
JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly || fail=1

step "tracing + fleet observability suite (tests/test_tracing.py)"
JAX_PLATFORMS=cpu python -m pytest tests/test_tracing.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly || fail=1

step "online continual-learning suite (tests/test_online.py + refit)"
JAX_PLATFORMS=cpu python -m pytest tests/test_online.py \
    tests/test_refit_serving.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly || fail=1

step "serving bench smoke (bench.py --serve --smoke)"
JAX_PLATFORMS=cpu python bench.py --serve --smoke || fail=1

step "fleet bench smoke (bench.py --serve-fleet --smoke)"
# gates: zero lost client requests under an injected replica crash +
# rolling publish + canary auto-rollback, router counters on /metrics;
# ISSUE 14: merged fleet scrape == sum of per-replica scrapes (both
# replicas contributing), >= 1 assembled cross-process trace, and the
# serve_slow stall fires >= 1 slo_burn
JAX_PLATFORMS=cpu python bench.py --serve-fleet --smoke || fail=1

step "online continual-learning bench smoke (bench.py --online --smoke)"
# gates (ISSUE 15): >= 3 generations published under sustained load
# with ZERO lost client requests, responses byte-identical to the
# generation that served them, freshness lag finite and under
# online_max_lag_s, the chaos spec (publish-fail retried, corrupt
# chunk skipped), and the mid-loop SIGTERM kill/resume drill
# (byte-exact resume, no served-version regression)
JAX_PLATFORMS=cpu python bench.py --online --smoke || fail=1

if [[ "${1:-}" != "--quick" ]]; then
    step "tier-1 (full suite, 870 s cap)"
    rm -f /tmp/_t1.log /tmp/_t1.xml
    # pass count comes from --junitxml, not the dot stream: one pytest
    # process writes one report file, so an orphaned/background pytest
    # interleaving ITS dots into the captured log can no longer skew
    # DOTS_PASSED (tools/junit_passed.py falls back to the dot grep
    # only when the timeout killed pytest before the XML was written)
    timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
        -m 'not slow' --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly \
        --junitxml=/tmp/_t1.xml -o junit_family=xunit2 \
        2>&1 | tee /tmp/_t1.log
    rc=${PIPESTATUS[0]}
    echo "DOTS_PASSED=$(python tools/junit_passed.py /tmp/_t1.xml /tmp/_t1.log)"
    [[ $rc -ne 0 ]] && fail=1
fi

echo
if [[ $fail -eq 0 ]]; then
    echo "verify: ALL GATES PASSED"
else
    echo "verify: FAILED (see the first failing gate above)"
fi
exit $fail
